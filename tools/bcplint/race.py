"""bcplint concurrency analysis: BCP007-BCP010 and the concurrency report.

Static lockset/race analysis over the threaded fleet. Three layers:

1. **Thread-root discovery** — ``threading.Thread(target=...)`` /
   ``Timer`` spawns, ``ThreadPoolExecutor.submit`` targets,
   ``ThreadingHTTPServer`` handler classes (``do_*``/``handle`` methods
   of ``BaseRequestHandler`` subclasses), and RPC dispatch entries
   (``@rpc_method`` handlers, which ``rpc/server.execute`` wraps in
   ``cs_main`` unless the handler sets ``no_cs_main``).
2. **Lockset inference** — every ``self.<attr>`` write/probe site gets
   the set of statically-held locks, tracked in document order through
   nested ``with`` blocks AND explicit ``.acquire()``/``.release()``
   pairs (the BCP003 held-region discipline generalized to all
   lock-shaped names).
3. **Per-root BFS** over a shallow typed call graph (param/return
   annotations, ``self.attr`` types from ``__init__``, container
   element types), carrying held-lockset states, attributing every
   write site to the roots that can reach it.

Rules:

- **BCP007** — shared attribute written from >=2 thread roots with an
  empty common lockset (no single lock consistently guards it).
- **BCP008** — compound non-GIL-atomic mutation (``x += 1``,
  check-then-mutate probe+mutation sequences — the PR 7 sigcache
  ``move_to_end``/evict lesson) on shared state outside any lock.
- **BCP009** — violation of a declared guard: the ``GUARDED_BY``
  convention (class-level ``GUARDED_BY = {"attr": "lock"}`` dict or a
  trailing ``# GUARDED_BY(lock)`` comment on the ``__init__`` assign)
  documents intent; this rule machine-enforces it at every write site.
- **BCP010** — a started thread/timer/executor stored on ``self`` with
  no ``join()``/``shutdown()``/``cancel()`` reachable from
  ``close()``/``stop()``/``__exit__`` (BCP002's pairing discipline
  extended from collectors to threads).

Everything unresolvable errs toward silence, same contract as the rest
of bcplint: a race lint that cries wolf gets baselined wholesale and
dies. The same model renders ``--concurrency-report``
(docs/CONCURRENCY.md): thread roots -> reached functions -> guarded
fields, so the concurrency model is a reviewable artifact.
"""

from __future__ import annotations

import ast
import re

from .engine import Finding, Module, iter_py_files
from .checks import (Check, _GLOBAL_LOCKS, _LOCKISH_RE, attr_parts,
                     call_terminal, const_str)

# methods whose call mutates the receiver in a way that composes with a
# preceding membership/get probe into a non-atomic compound sequence
_MUTATORS = {"append", "appendleft", "add", "pop", "popitem", "popleft",
             "remove", "discard", "clear", "update", "extend",
             "move_to_end", "setdefault", "insert"}
_PROBERS = {"get", "keys", "items", "values", "index", "count"}
_JOINERS = {"join", "shutdown", "cancel"}
# cross-thread marshaling: work handed to these runs on the event loop
# thread, never the caller's (call_soon/create_task stay attributed —
# same-thread scheduling)
_MARSHALERS = {"call_soon_threadsafe", "run_coroutine_threadsafe"}
_CLOSE_PREFIXES = ("close", "stop")
_CLOSEISH = {"close", "stop", "__exit__", "shutdown"}
_HANDLER_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
                  "StreamRequestHandler", "DatagramRequestHandler",
                  "BaseRequestHandler"}
_CONTAINERS = {"Sequence", "List", "list", "Iterable", "Tuple", "tuple",
               "set", "Set", "frozenset", "deque"}
_GUARD_COMMENT_RE = re.compile(r"#\s*GUARDED_BY\(([A-Za-z_][\w.]*)\)")


def _norm_lock(name: str) -> str:
    """Comparison form of a lock name: last dotted segment, leading
    underscores stripped — so a declared ``GUARDED_BY("ban_lock")``
    matches the observed ``CConnman._ban_lock``."""
    return name.split(".")[-1].lstrip("_")


def ann_type(ann) -> tuple[str | None, str | None]:
    """(scalar_type, element_type) names from an annotation node.
    ``Optional[X]`` -> X; ``Sequence[X]`` -> (None, X); single-typed
    ``Union`` unwrapped; string annotations parsed. None when opaque."""
    if ann is None:
        return (None, None)
    s = const_str(ann)
    if s is not None:
        try:
            ann = ast.parse(s, mode="eval").body
        except SyntaxError:
            return (None, None)
    if isinstance(ann, ast.Name):
        return (ann.id, None)
    if isinstance(ann, ast.Attribute):
        return (ann.attr, None)
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        sides = [ann_type(ann.left), ann_type(ann.right)]
        real = [t for t in sides if t[0] not in (None, "None")]
        return real[0] if len(real) == 1 else (None, None)
    if isinstance(ann, ast.Subscript):
        base = ann.value
        bname = (base.id if isinstance(base, ast.Name)
                 else base.attr if isinstance(base, ast.Attribute) else None)
        sl = ann.slice
        if bname == "Optional":
            return ann_type(sl)
        if bname == "Union" and isinstance(sl, ast.Tuple):
            real = [t for t in (ann_type(e) for e in sl.elts)
                    if t[0] not in (None, "None")]
            return real[0] if len(real) == 1 else (None, None)
        if bname in _CONTAINERS:
            elt = sl.elts[0] if isinstance(sl, ast.Tuple) and sl.elts else sl
            return (None, ann_type(elt)[0])
    return (None, None)


def _param_env(func) -> dict[str, tuple[str | None, str | None]]:
    env = {}
    for a in list(func.args.posonlyargs) + list(func.args.args) + list(
            func.args.kwonlyargs):
        t = ann_type(a.annotation)
        if t != (None, None):
            env[a.arg] = t
    return env


class ClassInfo:
    def __init__(self, mod: Module, node: ast.ClassDef, env):
        self.path = mod.path
        self.name = node.name
        self.node = node
        self.env = env  # closure: enclosing-function param types
        self.bases = [p[-1] for p in (attr_parts(b) for b in node.bases)
                      if p]
        self.methods = {n.name: n for n in node.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        self.attr_types: dict[str, str] = {}
        self.attr_elems: dict[str, str] = {}
        self.guards: dict[str, str] = {}     # attr -> declared lock
        self.guard_lines: dict[str, int] = {}
        self._collect_guards(mod)

    def _collect_guards(self, mod: Module) -> None:
        # class-level dict convention: GUARDED_BY = {"attr": "lock"}
        for stmt in self.node.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "GUARDED_BY"
                    and isinstance(stmt.value, ast.Dict)):
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    ks, vs = const_str(k), const_str(v)
                    if ks and vs:
                        self.guards[ks] = vs
                        self.guard_lines[ks] = stmt.lineno
        # trailing-comment convention on __init__ assigns:
        #     self.attr = ...  # GUARDED_BY(lock)
        init = self.methods.get("__init__")
        if init is None:
            return
        lines = mod.source.splitlines()
        for stmt in ast.walk(init):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                parts = attr_parts(t)
                if not (parts and len(parts) == 2 and parts[0] == "self"):
                    continue
                if 1 <= stmt.lineno <= len(lines):
                    m = _GUARD_COMMENT_RE.search(lines[stmt.lineno - 1])
                    if m:
                        self.guards.setdefault(parts[1], m.group(1))
                        self.guard_lines.setdefault(parts[1], stmt.lineno)


class FuncFacts:
    """Per-function facts, locksets relative to function entry."""

    def __init__(self, fid, qual, path):
        self.fid = fid          # (class_name | None, func_name)
        self.qual = qual        # "Class.meth" | "func"
        self.path = path
        self.writes = []        # (attr "T.a", kind, frozenset, line)
        self.probes = []        # (attr "T.a", frozenset, line)
        self.calls = []         # (callee fid, frozenset, line)
        self.spawns = []        # (bound_attr|None, target fid|None,
                                #  line, kind thread|timer|executor)
        self.starts = set()     # self attrs .start()ed
        self.joins = set()      # self attrs joined/shutdown/cancelled
        self.submits = []       # (target fid, line)


class Root:
    def __init__(self, fid, kind, concurrent, init_locks, path):
        self.fid = fid
        self.kind = kind
        self.concurrent = concurrent
        self.init_locks = frozenset(init_locks)
        self.path = path

    @property
    def name(self) -> str:
        cls, fn = self.fid
        return "%s.%s" % (cls, fn) if cls else fn


class Model:
    """The whole-tree concurrency model: classes, typed call facts,
    thread roots, and the per-root lockset reachability that the
    BCP007-BCP010 rules and the --concurrency-report both consume."""

    def __init__(self, mods):
        self.mods = mods
        self.all_classes: list[ClassInfo] = []
        self.classes: dict[str, ClassInfo] = {}  # unique names only
        self.by_cid: dict[str, ClassInfo] = {}
        self.modfuncs: dict[str, tuple[Module, ast.AST]] = {}
        self.rpc_funcs: dict[str, bool] = {}  # fname -> no_cs_main
        self.facts: dict[tuple, FuncFacts] = {}
        self.roots: dict[tuple, Root] = {}
        # BFS output
        self.reached: dict[str, set[str]] = {}     # root name -> quals
        self.attr_writes: dict[str, list] = {}     # attr -> site dicts
        self.attr_probes: dict[str, list] = {}
        self._built = False

    # -- pass 1: index classes + module functions -----------------------

    def _index(self) -> None:
        amb_funcs: set[str] = set()
        for mod in self.mods:
            self._index_node(mod, mod.tree, {}, top=True,
                             amb_funcs=amb_funcs)
        # same-named classes stay structurally analyzable under a
        # path-qualified id, but NAME-based type resolution only trusts
        # unique names (anything else errs toward silence)
        counts: dict[str, int] = {}
        for ci in self.all_classes:
            counts[ci.name] = counts.get(ci.name, 0) + 1
        for ci in self.all_classes:
            ci.cid = (ci.name if counts[ci.name] == 1
                      else "%s@%s" % (ci.name, ci.path))
            self.by_cid[ci.cid] = ci
            if counts[ci.name] == 1:
                self.classes[ci.name] = ci
        for name in amb_funcs:
            self.modfuncs.pop(name, None)
            self.rpc_funcs.pop(name, None)

    def _index_node(self, mod, node, env, top, amb_funcs) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self.all_classes.append(ClassInfo(mod, child, env))
                self._index_node(mod, child, env, False, amb_funcs)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if top and not isinstance(node, ast.ClassDef):
                    if child.name in self.modfuncs:
                        amb_funcs.add(child.name)
                    else:
                        self.modfuncs[child.name] = (mod, child)
                        if self._is_rpc(child):
                            self.rpc_funcs[child.name] = False
                env2 = dict(env)
                env2.update(_param_env(child))
                self._index_node(mod, child, env2, False, amb_funcs)
            else:
                self._index_node(mod, child, env, top, amb_funcs)
        if isinstance(node, ast.Module):
            # fn.no_cs_main = True module-level assigns
            for child in node.body:
                if (isinstance(child, ast.Assign)
                        and len(child.targets) == 1):
                    p = attr_parts(child.targets[0])
                    if (p and len(p) == 2 and p[1] == "no_cs_main"
                            and p[0] in self.rpc_funcs
                            and isinstance(child.value, ast.Constant)
                            and child.value.value is True):
                        self.rpc_funcs[p[0]] = True

    def _class(self, t):
        if not t:
            return None
        return self.by_cid.get(t) or self.classes.get(t)

    @staticmethod
    def _is_rpc(func) -> bool:
        for dec in func.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            parts = attr_parts(target) or []
            if parts and parts[-1] == "rpc_method":
                return True
        return False

    # -- pass 2: attr types from __init__ -------------------------------

    def _type_attrs(self) -> None:
        for ci in self.all_classes:
            init = ci.methods.get("__init__")
            if init is None:
                continue
            env = dict(ci.env)
            env.update(_param_env(init))
            for stmt in ast.walk(init):
                if isinstance(stmt, ast.AnnAssign):
                    parts = attr_parts(stmt.target)
                    if parts and len(parts) == 2 and parts[0] == "self":
                        t, e = ann_type(stmt.annotation)
                        if t and t in self.classes:
                            ci.attr_types.setdefault(parts[1], t)
                        if e and e in self.classes:
                            ci.attr_elems.setdefault(parts[1], e)
                    continue
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1):
                    continue
                parts = attr_parts(stmt.targets[0])
                if not (parts and len(parts) == 2 and parts[0] == "self"):
                    continue
                t, e = self._static_type(stmt.value, env)
                if t and t in self.classes:
                    ci.attr_types.setdefault(parts[1], t)
                if e and e in self.classes:
                    ci.attr_elems.setdefault(parts[1], e)
                # executors are lifecycle-tracked even though the class
                # is stdlib (not in self.classes)
                if t == "ThreadPoolExecutor":
                    ci.attr_types.setdefault(parts[1], t)
        # late construction ("self.x = None, set by start()") is the
        # dominant lifecycle idiom: a direct ClassName(...) assign in
        # any other method types the attr too (__init__ typed it first
        # above, so a conflicting late rebind never overrides it)
        for ci in self.all_classes:
            for mname, mnode in ci.methods.items():
                if mname == "__init__":
                    continue
                for stmt in ast.walk(mnode):
                    if not (isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.value, ast.Call)):
                        continue
                    parts = attr_parts(stmt.targets[0])
                    if not (parts and len(parts) == 2
                            and parts[0] == "self"):
                        continue
                    term = call_terminal(stmt.value)
                    if term and (term in self.classes
                                 or term == "ThreadPoolExecutor"):
                        ci.attr_types.setdefault(parts[1], term)

    def _static_type(self, expr, env):
        """Shallow (type, elem) of an __init__ rvalue: a typed param, a
        ClassName(...) construction, or list/tuple/sorted(param)."""
        if isinstance(expr, ast.Name):
            return env.get(expr.id, (None, None))
        if isinstance(expr, ast.Call):
            term = call_terminal(expr)
            if term in ("list", "tuple", "sorted") and expr.args:
                inner = expr.args[0]
                if isinstance(inner, ast.Name):
                    return (None, env.get(inner.id, (None, None))[1])
                return (None, None)
            if term and (term in self.classes
                         or term == "ThreadPoolExecutor"):
                return (term, None)
        return (None, None)

    # -- pass 3: per-function fact extraction ---------------------------

    def _scan_all(self) -> None:
        for ci in self.all_classes:
            for mname, mnode in ci.methods.items():
                env = dict(ci.env)
                env.update(_param_env(mnode))
                fid = (ci.cid, mname)
                self.facts[fid] = self._scan_func(
                    fid, "%s.%s" % (ci.name, mname), ci.path, mnode, ci,
                    env)
        for fname, (mod, fnode) in self.modfuncs.items():
            env = _param_env(fnode)
            if fname in self.rpc_funcs:
                # project convention (rpc/server.execute): handler
                # param0 is the Node instance, usually unannotated
                args = fnode.args.posonlyargs + fnode.args.args
                if args and args[0].arg not in env and "Node" in self.classes:
                    env[args[0].arg] = ("Node", None)
            fid = (None, fname)
            self.facts[fid] = self._scan_func(
                fid, fname, mod.path, fnode, None, env)

    def _scan_func(self, fid, qual, path, func, ci, env) -> FuncFacts:
        facts = FuncFacts(fid, qual, path)
        locals_t: dict[str, tuple] = {}   # name -> (type, elem)
        binds: dict[str, str] = {}        # name -> self attr (threads)
        owned: set[str] = set()  # locally-constructed => thread-private
        held: list[str] = []

        def lookup(name):
            return locals_t.get(name) or env.get(name) or (None, None)

        def is_owned(parts) -> bool:
            """Receiver rooted at an object this function constructed:
            thread-confined until published, so its state is not shared
            and calls through it are not attributed (the shadow-
            chainstate pattern — instance aliasing would otherwise
            charge the private copy's writes to the shared one)."""
            return bool(parts) and parts[0] in owned

        def chain_type(parts):
            """Type name of a self./Name. attribute chain, or None."""
            if not parts:
                return None
            if parts[0] == "self":
                if ci is None:
                    return None
                t = ci.cid
            else:
                t = lookup(parts[0])[0]
            for a in parts[1:]:
                tc = self._class(t)
                t = tc.attr_types.get(a) if tc else None
            return t

        def attr_of(parts):
            """Resolve a chain ending in a data attribute of a typed
            owner -> "Type.attr", or None."""
            if not parts or len(parts) < 2:
                return None
            owner_t = chain_type(parts[:-1])
            return "%s.%s" % (owner_t, parts[-1]) if owner_t else None

        def lock_name(expr):
            parts = attr_parts(expr)
            if not parts:
                return None
            term = parts[-1]
            if term in _GLOBAL_LOCKS:
                return term
            if not _LOCKISH_RE.search(term):
                return None
            if len(parts) >= 2:
                owner_t = chain_type(parts[:-1])
                if owner_t:
                    return "%s.%s" % (owner_t, term)
                return "%s.%s" % (parts[-2], term)
            return term

        def callee_fid(call):
            f = call.func
            if isinstance(f, ast.Name):
                if f.id in self.modfuncs:
                    return (None, f.id)
                return None
            if isinstance(f, ast.Attribute):
                recv = attr_parts(f.value)
                if recv is None:
                    return None
                rt = chain_type(recv)
                tc = self._class(rt)
                if tc and f.attr in tc.methods:
                    return (tc.cid, f.attr)
            return None

        def expr_type(expr):
            """(type, elem) of an rvalue: names, constructions, typed
            method calls via return annotations, list()/sorted()."""
            parts = attr_parts(expr)
            if parts:
                if len(parts) == 1:
                    return lookup(parts[0])
                t = chain_type(parts)
                if t:
                    return (t, None)
                tc = self._class(chain_type(parts[:-1]))
                if tc:
                    return (None, tc.attr_elems.get(parts[-1]))
                return (None, None)
            if isinstance(expr, ast.Call):
                term = call_terminal(expr)
                if term in ("list", "sorted", "tuple") and expr.args:
                    return (None, expr_type(expr.args[0])[1])
                if term and term in self.classes and isinstance(
                        expr.func, ast.Name):
                    return (term, None)
                fid2 = callee_fid(expr)
                if fid2 is not None:
                    node = (self.by_cid[fid2[0]].methods[fid2[1]]
                            if fid2[0] else self.modfuncs[fid2[1]][1])
                    return ann_type(node.returns)
            return (None, None)

        def spawn_kind(call):
            term = call_terminal(call)
            if term == "Thread":
                return "thread"
            if term == "Timer":
                return "timer"
            if term == "ThreadPoolExecutor":
                return "executor"
            return None

        def spawn_target(call, kind):
            target = None
            if kind == "thread":
                for kw in call.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif kind == "timer":
                if len(call.args) >= 2:
                    target = call.args[1]
                for kw in call.keywords:
                    if kw.arg == "function":
                        target = kw.value
            if target is None:
                return None
            parts = attr_parts(target)
            if parts and len(parts) == 2 and parts[0] == "self" and ci:
                if parts[1] in ci.methods:
                    return (ci.cid, parts[1])
            if parts and len(parts) == 1 and parts[0] in self.modfuncs:
                return (None, parts[0])
            return None

        def resolve_callable(expr):
            parts = attr_parts(expr)
            if not parts:
                return None
            if len(parts) == 1 and parts[0] in self.modfuncs:
                return (None, parts[0])
            tc = self._class(chain_type(parts[:-1]))
            if tc and parts[-1] in tc.methods:
                return (tc.cid, parts[-1])
            return None

        def on_call(call):
            term = call_terminal(call)
            f = call.func
            if isinstance(f, ast.Attribute):
                recv = attr_parts(f.value)
                # explicit lock discipline: document-order toggle
                if term in ("acquire", "release") and recv is not None:
                    ln = lock_name(f.value)
                    if ln:
                        if term == "acquire":
                            if ln not in held:
                                held.append(ln)
                        elif ln in held:
                            held.remove(ln)
                        return
                if term == "start" and recv and len(recv) == 2 \
                        and recv[0] == "self":
                    facts.starts.add(recv[1])
                    return
                if term in _JOINERS and recv is not None:
                    if len(recv) == 2 and recv[0] == "self":
                        facts.joins.add(recv[1])
                        return
                    if len(recv) == 1 and recv[0] in binds:
                        facts.joins.add(binds[recv[0]])
                        return
                if term == "submit" and recv is not None:
                    rt = chain_type(recv)
                    if rt == "ThreadPoolExecutor" and call.args:
                        tgt = resolve_callable(call.args[0])
                        if tgt is not None:
                            facts.submits.append((tgt, call.lineno))
                        return
                # chained fire-and-forget: threading.Thread(...).start()
                if term == "start" and isinstance(f.value, ast.Call):
                    k = spawn_kind(f.value)
                    if k:
                        tgt = spawn_target(f.value, k)
                        facts.spawns.append((None, tgt, call.lineno, k))
                        return
                if recv is not None and is_owned(recv):
                    return  # thread-private receiver: not attributed
                if recv is not None and term in _MUTATORS:
                    a = attr_of(recv)  # bare locals: out of scope
                    if a:
                        facts.writes.append(
                            (a, "mutcall", frozenset(held), call.lineno))
                if recv is not None and term in _PROBERS:
                    a = attr_of(recv)
                    if a:
                        facts.probes.append(
                            (a, frozenset(held), call.lineno))
            fid2 = callee_fid(call)
            if fid2 is not None and fid2[1] != "__init__":
                facts.calls.append((fid2, frozenset(held), call.lineno))

        def scan_expr(node):
            # manual walk so cross-thread marshaling is a boundary: the
            # callable/coroutine handed to loop.call_soon_threadsafe or
            # asyncio.run_coroutine_threadsafe executes on the event
            # loop thread, not here — descending into the args would
            # attribute the loop's writes to this root (err toward
            # silence; the loop root reaches them on its own edges)
            stack = [node]
            while stack:
                sub = stack.pop()
                if isinstance(sub, ast.Call) \
                        and call_terminal(sub) in _MARSHALERS:
                    if isinstance(sub.func, ast.Attribute):
                        stack.append(sub.func.value)  # receiver chain
                    continue
                stack.extend(ast.iter_child_nodes(sub))
                if isinstance(sub, ast.Call):
                    on_call(sub)
                elif isinstance(sub, ast.Compare) and any(
                        isinstance(op, (ast.In, ast.NotIn))
                        for op in sub.ops):
                    for comp in sub.comparators:
                        parts = attr_parts(comp)
                        a = (attr_of(parts)
                             if parts and not is_owned(parts) else None)
                        if a:
                            facts.probes.append(
                                (a, frozenset(held), sub.lineno))

        def record_write(target, kind, line):
            if isinstance(target, ast.Subscript):
                parts = attr_parts(target.value)
                a = (attr_of(parts)
                     if parts and not is_owned(parts) else None)
                if a:
                    facts.writes.append(
                        (a, "itemset", frozenset(held), line))
                return
            parts = attr_parts(target)
            if not parts or is_owned(parts):
                return
            a = attr_of(parts)
            if a:
                facts.writes.append((a, kind, frozenset(held), line))

        def handle_assign_pair(target, value, line):
            scan_expr(value)
            if isinstance(value, ast.Call):
                k = spawn_kind(value)
                if k:
                    bound = None
                    parts = attr_parts(target)
                    if parts and len(parts) == 2 and parts[0] == "self":
                        bound = parts[1]
                    tgt = (spawn_target(value, k)
                           if k != "executor" else None)
                    facts.spawns.append((bound, tgt, line, k))
            if isinstance(target, ast.Name):
                t = expr_type(value)
                if t != (None, None):
                    locals_t[target.id] = t
                if (isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Name)
                        and value.func.id in self.classes):
                    owned.add(target.id)
                else:
                    owned.discard(target.id)  # rebound to shared state
                vparts = attr_parts(value)
                if vparts and len(vparts) == 2 and vparts[0] == "self":
                    binds[target.id] = vparts[1]
                return
            if isinstance(value, ast.Name):
                owned.discard(value.id)  # published: escapes the thread
            record_write(target, "assign", line)

        def scan_stmt(st):
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                return
            if isinstance(st, ast.With):
                pushed = []
                for item in st.items:
                    scan_expr(item.context_expr)
                    ln = lock_name(item.context_expr)
                    if ln:
                        held.append(ln)
                        pushed.append(ln)
                scan_block(st.body)
                for ln in pushed:
                    if ln in held:
                        held.remove(ln)
                return
            if isinstance(st, ast.Assign):
                if (len(st.targets) == 1
                        and isinstance(st.targets[0], ast.Tuple)
                        and isinstance(st.value, ast.Tuple)
                        and len(st.targets[0].elts)
                        == len(st.value.elts)):
                    for t, v in zip(st.targets[0].elts, st.value.elts):
                        handle_assign_pair(t, v, st.lineno)
                    return
                for t in st.targets:
                    handle_assign_pair(t, st.value, st.lineno)
                return
            if isinstance(st, ast.AnnAssign) and st.value is not None:
                handle_assign_pair(st.target, st.value, st.lineno)
                return
            if isinstance(st, ast.AugAssign):
                scan_expr(st.value)
                record_write(st.target, "aug", st.lineno)
                return
            if isinstance(st, ast.For):
                scan_expr(st.iter)
                if isinstance(st.target, ast.Name):
                    iparts = attr_parts(st.iter)
                    elem = None
                    if iparts:
                        if len(iparts) == 1:
                            elem = lookup(iparts[0])[1]
                        else:
                            tc = self._class(chain_type(iparts[:-1]))
                            elem = (tc.attr_elems.get(iparts[-1])
                                    if tc else None)
                    if elem:
                        locals_t[st.target.id] = (elem, None)
                scan_block(st.body)
                scan_block(st.orelse)
                return
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.stmt):
                    scan_stmt(child)
                elif isinstance(child, ast.expr):
                    scan_expr(child)
                elif isinstance(child, ast.excepthandler):
                    scan_block(child.body)
                elif isinstance(child, (ast.withitem, ast.arguments)):
                    pass

        def scan_block(stmts):
            for st in stmts:
                scan_stmt(st)

        scan_block(func.body)
        return facts

    # -- pass 4: thread roots -------------------------------------------

    def _add_root(self, fid, kind, concurrent, init_locks, path) -> None:
        if fid is None or fid not in self.facts:
            return
        prev = self.roots.get(fid)
        if prev is None:
            self.roots[fid] = Root(fid, kind, concurrent, init_locks,
                                   path)
        else:
            prev.concurrent = prev.concurrent or concurrent

    def _find_roots(self) -> None:
        for facts in self.facts.values():
            for _bound, tgt, _line, kind in facts.spawns:
                self._add_root(tgt, kind, False, (), facts.path)
            for tgt, _line in facts.submits:
                self._add_root(tgt, "executor", True, (), facts.path)
        for ci in self.all_classes:
            if not any(b in _HANDLER_BASES for b in ci.bases):
                continue
            for mname in ci.methods:
                if mname.startswith("do_") or mname == "handle":
                    self._add_root((ci.cid, mname), "handler", True, (),
                                   ci.path)
        for fname, no_cs in self.rpc_funcs.items():
            init = () if no_cs else ("cs_main",)
            if (None, fname) in self.facts:
                self._add_root((None, fname), "rpc", True, init,
                               self.facts[(None, fname)].path)

    # -- pass 5: per-root lockset BFS -----------------------------------

    _MAX_LOCKSETS = 6  # distinct incoming locksets tracked per function

    def _reach(self) -> None:
        for root in self.roots.values():
            seen: dict[tuple, set] = {}
            stack = [(root.fid, root.init_locks)]
            reached = self.reached.setdefault(root.name, set())
            while stack:
                fid, inc = stack.pop()
                facts = self.facts.get(fid)
                if facts is None or fid[1] == "__init__":
                    continue
                states = seen.setdefault(fid, set())
                if inc in states or len(states) >= self._MAX_LOCKSETS:
                    continue
                states.add(inc)
                reached.add(facts.qual)
                for attr, kind, ls, line in facts.writes:
                    self.attr_writes.setdefault(attr, []).append({
                        "root": root, "locks": inc | ls,
                        "path": facts.path, "line": line, "kind": kind,
                        "qual": facts.qual, "fid": fid})
                for attr, ls, line in facts.probes:
                    self.attr_probes.setdefault(attr, []).append({
                        "root": root, "locks": inc | ls,
                        "path": facts.path, "line": line,
                        "qual": facts.qual, "fid": fid})
                for callee, ls, _line in facts.calls:
                    stack.append((callee, inc | ls))

    def build(self) -> None:
        if self._built:
            return
        self._built = True
        self._index()
        self._type_attrs()
        self._scan_all()
        self._find_roots()
        self._reach()

    # -- rules ----------------------------------------------------------

    def _declared_guard(self, attr: str) -> str | None:
        cls, _, name = attr.rpartition(".")
        ci = self._class(cls)
        return ci.guards.get(name) if ci else None

    def _shared(self, attr: str) -> bool:
        """>=2 distinct roots touch the attribute, or any writer root is
        itself concurrent (handler pool / executor / rpc dispatch)."""
        writes = self.attr_writes.get(attr, ())
        probes = self.attr_probes.get(attr, ())
        roots = {s["root"].name for s in writes}
        roots |= {s["root"].name for s in probes}
        if len(roots) >= 2:
            return True
        return any(s["root"].concurrent for s in writes)

    def _bcp008(self) -> tuple[list[Finding], set[str]]:
        out, flagged, seen = [], set(), set()
        for attr, sites in sorted(self.attr_writes.items()):
            if not self._shared(attr) or self._declared_guard(attr):
                continue
            short = attr.split(".")[-1]
            # (a) read-modify-write outside any lock
            for s in sorted(sites, key=lambda s: (s["path"], s["line"])):
                if s["kind"] != "aug" or s["locks"]:
                    continue
                anchor = "%s::compound:%s" % (s["qual"], short)
                if anchor in seen:
                    continue
                seen.add(anchor)
                flagged.add(attr)
                out.append(Finding(
                    "BCP008", s["path"], s["line"],
                    "compound mutation of shared %s outside any lock — "
                    "read-modify-write is not GIL-atomic (the += tear)"
                    % attr, anchor))
            # (b) check-then-mutate: a lockless membership/get probe and
            # a lockless mutation of the same attr in the same function
            probes = {p["fid"] for p in self.attr_probes.get(attr, ())
                      if not p["locks"]}
            for s in sorted(sites, key=lambda s: (s["path"], s["line"])):
                if s["kind"] not in ("mutcall", "itemset"):
                    continue
                if s["locks"] or s["fid"] not in probes:
                    continue
                anchor = "%s::compound:%s" % (s["qual"], short)
                if anchor in seen:
                    continue
                seen.add(anchor)
                flagged.add(attr)
                out.append(Finding(
                    "BCP008", s["path"], s["line"],
                    "check-then-mutate on shared %s outside any lock — "
                    "the probe and the mutation can interleave (the "
                    "PR 7 sigcache move_to_end/evict lesson)" % attr,
                    anchor))
        return out, flagged

    def _bcp007(self, flagged: set[str]) -> list[Finding]:
        out = []
        for attr, sites in sorted(self.attr_writes.items()):
            if attr in flagged or self._declared_guard(attr):
                continue
            roots = {s["root"].name for s in sites}
            if len(roots) < 2:
                continue
            common = frozenset.intersection(
                *(frozenset(s["locks"]) for s in sites))
            if common:
                continue
            first = min(sites, key=lambda s: (s["path"], s["line"]))
            out.append(Finding(
                "BCP007", first["path"], first["line"],
                "shared attribute %s is written from %d thread roots "
                "(%s) with no common lock — no lockset consistently "
                "guards it" % (attr, len(roots),
                               ", ".join(sorted(roots))),
                "race:%s" % attr))
        return out

    def _bcp009(self) -> list[Finding]:
        out, seen = [], set()
        # root-reached sites carry full locksets; unreached sites fall
        # back to their in-edge locksets (one level of the caller-holds
        # convention — crucial for --changed subset runs where the
        # reaching roots live in un-analyzed files), then to the
        # locally-recorded lockset
        reached_sites: dict[tuple, list] = {}
        for attr, sites in self.attr_writes.items():
            for s in sites:
                reached_sites.setdefault(
                    (attr, s["fid"], s["line"]), []).append(s["locks"])
        in_edges: dict[tuple, list] = {}
        for f2 in self.facts.values():
            for cfid, ls2, _ln in f2.calls:
                in_edges.setdefault(cfid, []).append(ls2)
        for ci in sorted(self.all_classes,
                         key=lambda c: (c.path, c.name)):
            for attr_name, guard in sorted(ci.guards.items()):
                attr = "%s.%s" % (ci.cid, attr_name)
                g = _norm_lock(guard)
                for facts in self.facts.values():
                    if facts.fid[0] != ci.cid or facts.fid[1] == "__init__":
                        continue
                    for wattr, _kind, ls, line in facts.writes:
                        if wattr != attr:
                            continue
                        key = (attr, facts.fid, line)
                        locksets = reached_sites.get(key)
                        if locksets is None:
                            callers = in_edges.get(facts.fid)
                            if callers:
                                locksets = [ls | c for c in callers]
                            else:
                                locksets = [ls]
                        if all(g in {_norm_lock(x) for x in lset}
                               for lset in locksets):
                            continue
                        anchor = "%s::guard:%s" % (facts.qual, attr_name)
                        if anchor in seen:
                            continue
                        seen.add(anchor)
                        out.append(Finding(
                            "BCP009", facts.path, line,
                            "write to %s without its declared guard %r "
                            "held — the GUARDED_BY annotation promises "
                            "every mutation happens under that lock"
                            % (attr, guard), anchor))
        return out

    def _bcp010(self) -> list[Finding]:
        out = []
        for ci in sorted(self.all_classes,
                         key=lambda c: (c.path, c.name)):
            spawned: dict[str, tuple] = {}   # attr -> (line, kind)
            started: set[str] = set()
            for mname in ci.methods:
                facts = self.facts.get((ci.cid, mname))
                if facts is None:
                    continue
                for bound, _tgt, line, kind in facts.spawns:
                    if bound is not None:
                        spawned.setdefault(bound, (line, kind, mname))
                started |= facts.starts
            if not spawned:
                continue
            # close-ish closure over self-calls (BCP002 discipline)
            closeish = {m for m in ci.methods
                        if m in _CLOSEISH
                        or m.startswith(_CLOSE_PREFIXES)}
            frontier = list(closeish)
            while frontier:
                facts = self.facts.get((ci.cid, frontier.pop()))
                if facts is None:
                    continue
                for (ccls, cm), _ls, _line in facts.calls:
                    if ccls == ci.cid and cm not in closeish:
                        closeish.add(cm)
                        frontier.append(cm)
            credited: set[str] = set()
            for m in closeish:
                facts = self.facts.get((ci.cid, m))
                if facts is not None:
                    credited |= facts.joins
            for attr, (line, kind, _mname) in sorted(spawned.items()):
                live = attr in started or kind == "executor"
                if not live or attr in credited:
                    continue
                out.append(Finding(
                    "BCP010", ci.path, line,
                    "%s %s.%s is started but no join()/shutdown()/"
                    "cancel() on it is reachable from close()/stop()/"
                    "__exit__ — the thread outlives its owner (BCP002 "
                    "pairing extended to threads)" % (kind, ci.name, attr),
                    "%s::lifecycle:%s" % (ci.name, attr)))
        return out

    def findings(self) -> list[Finding]:
        self.build()
        comp, flagged = self._bcp008()
        out = self._bcp007(flagged) + comp + self._bcp009() + \
            self._bcp010()
        out.sort(key=lambda f: (f.path, f.line, f.rule, f.anchor))
        return out

    # -- the concurrency report -----------------------------------------

    def report(self) -> str:
        self.build()
        lines = [
            "# Concurrency model (generated)",
            "",
            "Generated by `python -m tools.bcplint.cli "
            "--concurrency-report > docs/CONCURRENCY.md`. Do not edit "
            "by hand — CI asserts this file regenerates byte-identically",
            "from the committed tree.",
            "",
            "## Thread roots",
            "",
            "| root | kind | concurrent | entry lockset | defined in |",
            "|---|---|---|---|---|",
        ]
        rpc_roots = []
        plain = []
        for fid in sorted(self.roots, key=lambda f: (f[0] or "", f[1])):
            r = self.roots[fid]
            (rpc_roots if r.kind == "rpc" else plain).append(r)
        for r in plain:
            lines.append("| `%s` | %s | %s | %s | `%s` |" % (
                r.name, r.kind, "yes" if r.concurrent else "no",
                "{%s}" % ", ".join(sorted(r.init_locks)) or "{}",
                r.path))
        if rpc_roots:
            no_cs = sorted(r.name for r in rpc_roots if not r.init_locks)
            lines.append(
                "| `rpc:*` (%d handlers) | rpc | yes | {cs_main}%s | "
                "`bitcoincashplus_tpu/rpc/` |" % (
                    len(rpc_roots),
                    " except no_cs_main: " + ", ".join(no_cs)
                    if no_cs else ""))
        lines += ["", "## Reachability", ""]
        for r in plain:
            reached = sorted(self.reached.get(r.name, ()))
            lines.append("### `%s`" % r.name)
            lines.append("")
            for q in reached:
                lines.append("- `%s`" % q)
            if not reached:
                lines.append("- (nothing resolvable)")
            lines.append("")
        if rpc_roots:
            union = set()
            for r in rpc_roots:
                union |= self.reached.get(r.name, set())
            lines.append("### `rpc:*` (%d handlers, combined)" %
                         len(rpc_roots))
            lines.append("")
            for q in sorted(union):
                lines.append("- `%s`" % q)
            lines.append("")
        lines += ["## Guarded state", "",
                  "| attribute | declared guard | write sites | "
                  "locks seen at writes |", "|---|---|---|---|"]
        any_guard = False
        for ci in sorted(self.all_classes,
                         key=lambda c: (c.path, c.name)):
            for attr_name, guard in sorted(ci.guards.items()):
                any_guard = True
                attr = "%s.%s" % (ci.cid, attr_name)
                # root-reached sites carry the caller's held locks too
                # (the caller-holds convention BCP009 validates); fall
                # back to the locally-recorded lockset when unreached
                reached = {}
                for s in self.attr_writes.get(attr, ()):
                    reached.setdefault(
                        (s["fid"], s["line"]), set()).update(s["locks"])
                nsites = 0
                locks = set()
                for facts in self.facts.values():
                    if facts.fid[0] != ci.cid or facts.fid[1] == "__init__":
                        continue
                    for wattr, _k, ls, line in facts.writes:
                        if wattr == attr:
                            nsites += 1
                            locks |= reached.get((facts.fid, line), ls)
                lines.append("| `%s` | `%s` | %d | %s |" % (
                    attr, guard, nsites,
                    ", ".join("`%s`" % x for x in sorted(locks))
                    or "—"))
        if not any_guard:
            lines.append("| — | — | — | — |")
        lines.append("")
        return "\n".join(lines)


class ConcurrencyAnalysis(Check):
    """BCP007-BCP010: cross-thread lockset/race analysis (one Check
    emitting four rules — they share the model build)."""

    rule = "BCP007"
    title = "cross-thread lockset/race analysis"
    catalog = [
        ("BCP007", "shared write from >=2 thread roots, no common lock"),
        ("BCP008", "compound non-GIL-atomic mutation outside any lock"),
        ("BCP009", "GUARDED_BY declared-guard violation"),
        ("BCP010", "started thread with no join reachable from close"),
    ]

    def __init__(self):
        self._mods: list[Module] = []

    def collect(self, mod: Module) -> None:
        self._mods.append(mod)

    def finalize(self, ctx) -> list[Finding]:
        return Model(self._mods).findings()


def build_model(root: str, paths=None) -> Model:
    import os
    root = os.path.abspath(root)
    if paths is None:
        paths = [os.path.join(root, "bitcoincashplus_tpu"),
                 os.path.join(root, "tools")]
    mods = []
    for abspath in iter_py_files(paths):
        try:
            mods.append(Module(root, abspath))
        except SyntaxError:
            continue
    model = Model(mods)
    model.build()
    return model


def build_report(root: str, paths=None) -> str:
    return build_model(root, paths).report()
