"""bcplint checks BCP001-BCP006.

Each check is a two-phase object: ``collect(module)`` gathers per-file
facts from the AST, ``finalize(ctx)`` folds them into Findings — so the
cross-module rules (native-family ownership, lock-order cycles, fault-
site parity) see the whole tree before judging any one file.

All analysis is syntactic and deliberately shallow: constant arguments,
one level of name resolution inside a function, for-loop constant
propagation over literal tuples. Anything unresolvable errs toward
silence — a lint that cries wolf gets baselined wholesale and dies.
"""

from __future__ import annotations

import ast
import os
import re

from .engine import Finding, Module, iter_py_files


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def attr_parts(node) -> list[str] | None:
    """``self.node.cs_main`` -> ["self", "node", "cs_main"]; None when the
    expression is not a pure Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def call_terminal(call: ast.Call) -> str | None:
    """Terminal name of the called expression: ``a.b.c()`` -> "c"."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def iter_funcs(tree):
    """Yields (qualname, func_node, enclosing_class_node_or_None) for
    every function/method, including nested ones (qualname dot-joined)."""
    def walk(node, prefix, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + child.name if prefix else child.name
                yield qual, child, cls
                yield from walk(child, qual + ".", cls)
            elif isinstance(child, ast.ClassDef):
                qual = prefix + child.name if prefix else child.name
                yield from walk(child, qual + ".", child)
    yield from walk(tree, "", None)


def local_assignments(func: ast.AST) -> dict[str, ast.AST]:
    """Simple ``name = expr`` bindings in a function body (last wins)."""
    out: dict[str, ast.AST] = {}
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                out[t.id] = node.value
    return out


def contains_snapshot_call(node) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            term = call_terminal(sub)
            if term and "snapshot" in term:
                return True
    return False


def find_cycles(edges: dict[tuple[str, str], str]):
    """SCCs with >1 node (or a self-loop) in the directed graph given as
    ``{(a, b): site}``; returns [(sorted_locks, {(a,b): site})]."""
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]
    for root in adj:
        if root in index:
            continue
        work = [(root, 0)]
        while work:
            node, i = work.pop()
            if i == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            succs = adj[node]
            while i < len(succs):
                w = succs[i]
                i += 1
                if w not in index:
                    work.append((node, i))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    out = []
    for scc in sccs:
        members = set(scc)
        if len(scc) < 2 and not any((n, n) in edges for n in scc):
            continue
        cyc = {(a, b): s for (a, b), s in edges.items()
               if a in members and b in members}
        out.append((sorted(members), cyc))
    return out


class Check:
    rule = "BCP000"
    title = ""

    def collect(self, mod: Module) -> None:
        raise NotImplementedError

    def finalize(self, ctx) -> list[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# BCP001 — telemetry namespace discipline (the PR 6 in_flight/TYPE lesson)
# ---------------------------------------------------------------------------

_TELEMETRY_OWNERS = {"tm", "telemetry", "REGISTRY"}
_FAMILY_KINDS = {"counter", "gauge", "histogram"}


class TelemetryNamespace(Check):
    """A registry collector must never emit a family name owned by a
    native Counter/Gauge/Histogram (same name, two TYPE lines in the
    exposition), must not project under a prefix that shadows native
    family names without justification, and must not stamp
    ``typ="counter"`` onto a point-in-time snapshot projection."""

    rule = "BCP001"
    title = "telemetry namespace discipline"

    def __init__(self):
        self.natives: dict[str, tuple[str, int, str]] = {}  # name -> site
        self.emits = []       # (mod, line, qual, name)
        self.flats = []       # (mod, line, qual, prefix, typ, snapshotish)

    def collect(self, mod: Module) -> None:
        for qual, func, _cls in iter_funcs(mod.tree):
            assigns = local_assignments(func)
            for node in ast.walk(func):
                if isinstance(node, ast.Call):
                    self._on_call(mod, qual, node, assigns)
                elif isinstance(node, ast.Dict):
                    self._on_dict(mod, qual, node)
        # module-level natives (the common case) and dict emissions
        for node in ast.iter_child_nodes(mod.tree):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    self._maybe_native(mod, sub)

    def _maybe_native(self, mod, call: ast.Call) -> None:
        f = call.func
        kind = None
        if isinstance(f, ast.Attribute) and f.attr in _FAMILY_KINDS:
            owner = attr_parts(f.value)
            if owner and owner[-1] in _TELEMETRY_OWNERS:
                kind = f.attr
        elif isinstance(f, ast.Name) and f.id in _FAMILY_KINDS:
            kind = f.id
        if kind is None:
            return
        name = const_str(call.args[0]) if call.args else None
        if name and name not in self.natives:
            self.natives[name] = (mod.path, call.lineno, kind)

    def _on_call(self, mod, qual, call: ast.Call, assigns) -> None:
        self._maybe_native(mod, call)
        if call_terminal(call) != "flat_families":
            return
        prefix = const_str(call.args[0]) if call.args else None
        if prefix is None:
            return
        typ = "gauge"
        for kw in call.keywords:
            if kw.arg == "typ":
                typ = const_str(kw.value) or "?"
        if len(call.args) >= 3:
            typ = const_str(call.args[2]) or typ
        data = call.args[1] if len(call.args) >= 2 else None
        snapshotish = False
        if data is not None:
            expr = data
            if isinstance(data, ast.Name) and data.id in assigns:
                expr = assigns[data.id]
            snapshotish = contains_snapshot_call(expr)
        self.flats.append((mod.path, call.lineno, qual, prefix, typ,
                           snapshotish))

    def _on_dict(self, mod, qual, node: ast.Dict) -> None:
        keys = {const_str(k) for k in node.keys if k is not None}
        if "name" not in keys or "samples" not in keys:
            return
        for k, v in zip(node.keys, node.values):
            if const_str(k) == "name":
                name = const_str(v)
                if name:
                    self.emits.append((mod.path, node.lineno, qual, name))

    def finalize(self, ctx) -> list[Finding]:
        out = []
        for path, line, qual, name in self.emits:
            if name in self.natives:
                npath, nline, kind = self.natives[name]
                out.append(Finding(
                    self.rule, path, line,
                    "collector emits family %r owned by the native %s at "
                    "%s:%d (duplicate family/TYPE in the exposition)"
                    % (name, kind, npath, nline),
                    "%s::emit:%s" % (qual, name)))
        for path, line, qual, prefix, typ, snapshotish in self.flats:
            shadowed = sorted(n for n in self.natives
                              if n.startswith(prefix + "_"))
            if shadowed:
                out.append(Finding(
                    self.rule, path, line,
                    "flat_families prefix %r shadows native family "
                    "namespace (%s) — a snapshot key matching a native "
                    "suffix would duplicate its family/TYPE"
                    % (prefix, ", ".join(shadowed[:3])
                       + (", ..." if len(shadowed) > 3 else "")),
                    "%s::flat:%s" % (qual, prefix)))
            if typ == "counter" and snapshotish:
                out.append(Finding(
                    self.rule, path, line,
                    "flat_families(typ=\"counter\") over a snapshot() "
                    "projection — non-monotonic families must export "
                    "typ=\"gauge\" or justify monotonicity",
                    "%s::counter-snapshot:%s" % (qual, prefix)))
        return out


# ---------------------------------------------------------------------------
# BCP002 — register/unregister pairing (the closure-leak lesson)
# ---------------------------------------------------------------------------

_CLOSEISH = {"close", "stop", "__exit__", "shutdown"}


class RegisterPairing(Check):
    """Every ``registry.register_collector`` / watchdog ``register`` in a
    class must have a matching unregister reachable from a close-ish
    method (close/stop/__exit__/shutdown, following self-calls) — else
    the registry closure pins the instance for the process lifetime."""

    rule = "BCP002"
    title = "register/unregister pairing"

    def __init__(self):
        self.classes = []  # (mod.path, class_name, regs, unregs, wildcard)

    @staticmethod
    def _reg_kind(call: ast.Call) -> str | None:
        term = call_terminal(call)
        if term == "register_collector":
            return "collector"
        if term == "register":
            owner = (attr_parts(call.func.value)
                     if isinstance(call.func, ast.Attribute) else None)
            if owner and owner[-1] == "WATCHDOG":
                return "watchdog"
        return None

    @staticmethod
    def _unreg_kind(call: ast.Call) -> str | None:
        term = call_terminal(call)
        if term == "unregister_collector":
            return "collector"
        if term == "unregister":
            owner = (attr_parts(call.func.value)
                     if isinstance(call.func, ast.Attribute) else None)
            if owner and owner[-1] == "WATCHDOG":
                return "watchdog"
        return None

    def collect(self, mod: Module) -> None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = {n.name: n for n in node.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            # close-ish reachability: close-ish methods plus the
            # transitive closure of their self.X() calls
            reachable = set(m for m in methods if m in _CLOSEISH)
            frontier = list(reachable)
            while frontier:
                body = methods[frontier.pop()]
                for sub in ast.walk(body):
                    if isinstance(sub, ast.Call):
                        parts = (attr_parts(sub.func)
                                 if isinstance(sub.func, ast.Attribute)
                                 else None)
                        if (parts and len(parts) == 2
                                and parts[0] == "self"
                                and parts[1] in methods
                                and parts[1] not in reachable):
                            reachable.add(parts[1])
                            frontier.append(parts[1])

            regs = []     # (kind, name, line)
            unregs = set()   # (kind, name)
            wildcard = set()  # kinds with an unresolvable unregister arg
            for mname, body in methods.items():
                loop_consts = self._loop_consts(body)
                for sub in ast.walk(body):
                    if not isinstance(sub, ast.Call):
                        continue
                    ukind = self._unreg_kind(sub)
                    if ukind and mname in reachable:
                        names = self._resolve_names(sub, loop_consts)
                        if names is None:
                            wildcard.add(ukind)
                        else:
                            unregs.update((ukind, n) for n in names)
                        continue
                    rkind = self._reg_kind(sub)
                    if rkind and mname not in _CLOSEISH:
                        name = const_str(sub.args[0]) if sub.args else None
                        if name:  # dynamic registration names: out of scope
                            regs.append((rkind, name, sub.lineno))
            if regs:
                self.classes.append(
                    (mod.path, node.name, regs, unregs, wildcard))

    @staticmethod
    def _loop_consts(body) -> dict[str, set[str]]:
        """``for name in ("a", "b"):`` -> {"name": {"a", "b"}} — the
        constant propagation the close() unregister loop pattern needs."""
        out: dict[str, set[str]] = {}
        for sub in ast.walk(body):
            if (isinstance(sub, ast.For)
                    and isinstance(sub.target, ast.Name)
                    and isinstance(sub.iter, (ast.Tuple, ast.List))):
                consts = {const_str(e) for e in sub.iter.elts}
                if None not in consts:
                    out.setdefault(sub.target.id, set()).update(consts)
        return out

    @staticmethod
    def _resolve_names(call: ast.Call, loop_consts) -> set[str] | None:
        if not call.args:
            return None
        arg = call.args[0]
        s = const_str(arg)
        if s is not None:
            return {s}
        if isinstance(arg, ast.Name) and arg.id in loop_consts:
            return loop_consts[arg.id]
        return None  # unresolvable -> wildcard (suppresses the pairing)

    def finalize(self, ctx) -> list[Finding]:
        out = []
        for path, cls, regs, unregs, wildcard in self.classes:
            for kind, name, line in regs:
                if kind in wildcard or (kind, name) in unregs:
                    continue
                out.append(Finding(
                    self.rule, path, line,
                    "%s registration %r in class %s has no matching "
                    "unregister reachable from close()/stop() — the "
                    "registry closure outlives the instance"
                    % (kind, name, cls),
                    "%s::%s:%s" % (cls, kind, name)))
        return out


# ---------------------------------------------------------------------------
# BCP003 — no blocking calls under cs_main (PR 2 banlist / PR 7 verify-wait)
# ---------------------------------------------------------------------------

_BLOCKING_ATTRS = {"fsync", "fdatasync", "sleep", "result", "wait",
                   "wait_for", "commit", "wal_checkpoint"}
_BLOCKING_NAMES = {"fsync", "sleep"}


class BlockingUnderCsMain(Check):
    """Inside a ``with ...cs_main:`` block, flag direct calls that can
    block indefinitely or hit disk: fsync, Future.result, condvar wait,
    sleep, sqlite commit/checkpoint. An explicit ``cs_main.release()``
    earlier in the block suspends the check until the paired
    ``acquire()`` (the PR 7 verify-wait pattern)."""

    rule = "BCP003"
    title = "no blocking calls under cs_main"

    def __init__(self):
        self.findings: list[Finding] = []

    @staticmethod
    def _is_cs_main(expr) -> bool:
        parts = attr_parts(expr)
        return bool(parts) and parts[-1] == "cs_main"

    def collect(self, mod: Module) -> None:
        for qual, func, _cls in iter_funcs(mod.tree):
            for node in func.body:
                self._scan_stmt(mod, qual, node, under=False,
                                released=[False])

    def _scan_stmt(self, mod, qual, node, under, released) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # closures execute later, outside the lock
        if isinstance(node, ast.With):
            takes = any(self._is_cs_main(item.context_expr)
                        for item in node.items)
            inner_under = under or takes
            state = [False] if (takes and not under) else released
            for child in node.body:
                self._scan_stmt(mod, qual, child, inner_under, state)
            return
        # document order: expressions flagged as seen, child statements
        # recursed — so an explicit cs_main.release() suspends flagging
        # for everything after it until the paired acquire()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._scan_stmt(mod, qual, child, under, released)
            else:
                self._scan_expr(mod, qual, child, under, released)

    def _scan_expr(self, mod, qual, node, under, released) -> None:
        if isinstance(node, ast.Lambda):
            return  # deferred execution
        if isinstance(node, ast.Call):
            term = call_terminal(node)
            if (term in ("release", "acquire")
                    and isinstance(node.func, ast.Attribute)
                    and self._is_cs_main(node.func.value)):
                released[0] = (term == "release")
            elif under and not released[0]:
                self._maybe_flag(mod, qual, node)
        for child in ast.iter_child_nodes(node):
            self._scan_expr(mod, qual, child, under, released)

    def _maybe_flag(self, mod, qual, call: ast.Call) -> None:
        f = call.func
        name = None
        if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
            if f.attr in ("release", "acquire"):
                return
            name = f.attr
        elif isinstance(f, ast.Name) and f.id in _BLOCKING_NAMES:
            name = f.id
        if name is None:
            return
        self.findings.append(Finding(
            self.rule, mod.path, call.lineno,
            "blocking call .%s() while cs_main is statically held — "
            "release around it (PR 7 verify-wait pattern) or move the "
            "I/O outside the lock (PR 2 banlist pattern)" % name,
            "%s::%s" % (qual, name)))

    def finalize(self, ctx) -> list[Finding]:
        # dedupe repeated identical anchors (same call name, same func)
        seen: set[str] = set()
        out = []
        for f in self.findings:
            if f.anchor in seen:
                continue
            seen.add(f.anchor)
            out.append(f)
        return out


# ---------------------------------------------------------------------------
# BCP004 — lock-acquisition-order extraction + cycle detection
# ---------------------------------------------------------------------------

_GLOBAL_LOCKS = {"cs_main", "notify_cv"}
_LOCKISH_RE = re.compile(
    r"(^cs_main$|^notify_cv$|_lock$|_cond$|_cv$|^lock$|^mutex$|_mu$)")


class LockOrder(Check):
    """Extract the static lock-order graph from nested ``with`` blocks
    over lock-shaped attributes, across every module, and report cycles.
    The runtime half (util/lockwatch, BCP_LOCKWATCH=1) sees through the
    indirection this syntactic pass cannot."""

    rule = "BCP004"
    title = "lock-order cycle detection"

    def __init__(self):
        self.edges: dict[tuple[str, str], str] = {}  # (a, b) -> site

    def _lock_name(self, expr, cls) -> str | None:
        parts = attr_parts(expr)
        if not parts:
            return None
        term = parts[-1]
        if term in _GLOBAL_LOCKS:
            return term
        if not _LOCKISH_RE.search(term):
            return None
        if len(parts) >= 2 and parts[-2] != "self":
            return "%s.%s" % (parts[-2], term)
        if cls is not None:
            return "%s.%s" % (cls.name, term)
        return term

    def collect(self, mod: Module) -> None:
        for _qual, func, cls in iter_funcs(mod.tree):
            self._scan(mod, cls, func.body, held=[])

    def _explicit_pair(self, stmt) -> tuple[str, ast.expr] | None:
        """``lock.acquire()`` / ``lock.release()`` as a bare statement
        -> ("acquire"|"release", lock_expr)."""
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)):
            return None
        term = stmt.value.func.attr
        if term not in ("acquire", "release"):
            return None
        return term, stmt.value.func.value

    def _scan(self, mod, cls, stmts, held) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate scope, scanned by iter_funcs
            if isinstance(stmt, ast.With):
                names = [n for n in (
                    self._lock_name(item.context_expr, cls)
                    for item in stmt.items) if n]
                pushed = []
                for n in names:
                    for h in held:
                        if h != n and (h, n) not in self.edges:
                            self.edges[(h, n)] = (mod.path, stmt.lineno)
                    held.append(n)
                    pushed.append(n)
                self._scan(mod, cls, stmt.body, held)
                for n in pushed:
                    if n in held:  # an explicit release() may have
                        held.remove(n)  # dropped it inside the block
                continue
            # explicit .acquire()/.release() document-order pairs mint
            # the same edges as nested with blocks (the gateway/banlist
            # idiom BCP004 was blind to)
            pair = self._explicit_pair(stmt)
            if pair is not None:
                term, lock_expr = pair
                n = self._lock_name(lock_expr, cls)
                if n:
                    if term == "acquire":
                        for h in held:
                            if h != n and (h, n) not in self.edges:
                                self.edges[(h, n)] = (mod.path,
                                                      stmt.lineno)
                        if n not in held:
                            held.append(n)
                    elif n in held:
                        held.remove(n)
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub and isinstance(sub, list):
                    self._scan(mod, cls, sub, held)
            for handler in getattr(stmt, "handlers", ()):
                self._scan(mod, cls, handler.body, held)

    def finalize(self, ctx) -> list[Finding]:
        out = []
        for locks, cyc in find_cycles(self.edges):
            path, line = min(cyc.values())
            legs = "; ".join("%s->%s at %s:%d" % (a, b, p, ln)
                             for (a, b), (p, ln) in sorted(cyc.items()))
            out.append(Finding(
                self.rule, path, line,
                "lock-order cycle between {%s}: %s — two paths take "
                "these locks in opposite orders (latent deadlock)"
                % (", ".join(locks), legs),
                "cycle:%s" % "<->".join(locks)))
        return out


# ---------------------------------------------------------------------------
# BCP005 — fault-site parity (every declared site drilled by some test)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"[^a-z0-9_]+")


class FaultSiteParity(Check):
    """Every fault site declared in util/faults.py (the SITES tuple) or
    as a module-level ``*_SITE = "..."`` constant anywhere must appear in
    at least one test — an undrilled crash/poison site is dead armor."""

    rule = "BCP005"
    title = "fault-site parity"

    def __init__(self):
        self.sites: dict[str, tuple[str, int]] = {}  # site -> decl site
        self.symbols: dict[str, set[str]] = {}  # site -> declaring consts

    def collect(self, mod: Module) -> None:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name):
                continue
            if (t.id == "SITES" and mod.path.endswith("util/faults.py")
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                for e in node.value.elts:
                    s = const_str(e)
                    if s:
                        self.sites.setdefault(s, (mod.path, e.lineno))
            elif t.id.endswith("_SITE"):
                s = const_str(node.value)
                if s:
                    self.sites.setdefault(s, (mod.path, node.lineno))
                    self.symbols.setdefault(s, set()).add(t.id)

    def finalize(self, ctx) -> list[Finding]:
        tests_dir = ctx.get("tests_dir")
        if not self.sites or not tests_dir:
            return []
        tokens: set[str] = set()
        names: set[str] = set()  # identifiers: symbolic site references
        for path in iter_py_files([tests_dir]):
            try:
                with open(path, "rb") as f:
                    tree = ast.parse(f.read().decode("utf-8", "replace"))
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                s = const_str(node)
                if s:
                    tokens.update(_TOKEN_RE.split(s))
                elif isinstance(node, ast.Name):
                    names.add(node.id)
                elif isinstance(node, ast.Attribute):
                    names.add(node.attr)
        out = []
        for site, (path, line) in sorted(self.sites.items()):
            if site in tokens:
                continue
            if self.symbols.get(site, set()) & names:
                continue  # drilled via the declaring constant's symbol
            out.append(Finding(
                self.rule, path, line,
                "fault site %r is declared but appears in no test "
                "under %s — undrilled crash/poison armor"
                % (site, os.path.basename(tests_dir)),
                "site:%s" % site))
        return out


# ---------------------------------------------------------------------------
# BCP006 — jit-tracing hygiene
# ---------------------------------------------------------------------------

_COERCERS = {"int", "float", "bool"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


class JitHygiene(Check):
    """Inside a jitted body, ``int()/float()/bool()`` of a traced value
    forces a trace-time concretization error (or worse, a silent
    host sync); and every devicewatch-watched program must declare a
    shape budget somewhere, or the retrace sentinel can only count."""

    rule = "BCP006"
    title = "jit-tracing hygiene"

    def __init__(self):
        self.coercions: list[Finding] = []
        self.programs: dict[str, list[tuple[str, int, bool]]] = {}

    @staticmethod
    def _jit_static_names(func) -> tuple[bool, set[str]]:
        """(is_jitted, static_argnames) from the decorator list."""
        for dec in func.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            parts = attr_parts(target) or []
            term = parts[-1] if parts else None
            if term == "jit":
                return True, set()
            if term == "partial" and isinstance(dec, ast.Call):
                inner = dec.args[0] if dec.args else None
                iparts = attr_parts(inner) or []
                if iparts and iparts[-1] == "jit":
                    statics: set[str] = set()
                    for kw in dec.keywords:
                        if kw.arg in ("static_argnames", "static_argnums"):
                            v = kw.value
                            s = const_str(v)
                            if s:
                                statics.add(s)
                            elif isinstance(v, (ast.Tuple, ast.List)):
                                statics.update(
                                    x for x in (const_str(e)
                                                for e in v.elts) if x)
                    return True, statics
        return False, set()

    @staticmethod
    def _static_valued(expr, statics) -> bool:
        """Heuristically static at trace time: constants, static args,
        len()/shape/dtype projections."""
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, ast.Name) and expr.id in statics:
            return True
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and call_terminal(sub) == "len":
                return True
            if (isinstance(sub, ast.Attribute)
                    and sub.attr in _STATIC_ATTRS):
                return True
        return False

    def collect(self, mod: Module) -> None:
        for qual, func, _cls in iter_funcs(mod.tree):
            jitted, statics = self._jit_static_names(func)
            if jitted:
                self._scan_jit_body(mod, qual, func, statics)
            for node in ast.walk(func):
                if isinstance(node, ast.Call):
                    self._maybe_program(mod, node)
        for node in ast.iter_child_nodes(mod.tree):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    self._maybe_program(mod, sub)

    def _scan_jit_body(self, mod, qual, func, statics) -> None:
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Name) and f.id in _COERCERS):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if self._static_valued(arg, statics):
                continue
            try:
                rendered = ast.unparse(arg)[:40]
            except Exception:
                rendered = "?"
            self.coercions.append(Finding(
                self.rule, mod.path, node.lineno,
                "%s(%s) inside a jitted body coerces a traced value to "
                "a Python scalar — concretization error at trace time"
                % (f.id, rendered),
                "%s::coerce:%s:%s" % (qual, f.id, rendered)))

    def _maybe_program(self, mod, call: ast.Call) -> None:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "program"):
            return
        owner = attr_parts(f.value)
        if not owner or owner[-1] not in ("dw", "devicewatch"):
            return
        name = const_str(call.args[0]) if call.args else None
        if not name:
            return
        budgeted = len(call.args) >= 2 or any(
            kw.arg == "shape_budget" and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None)
            for kw in call.keywords)
        self.programs.setdefault(name, []).append(
            (mod.path, call.lineno, budgeted))

    def finalize(self, ctx) -> list[Finding]:
        out = list(self.coercions)
        for name, sites in sorted(self.programs.items()):
            if any(b for _, _, b in sites):
                continue  # a budgeted registration upgrades the watch
            path, line, _ = sites[0]
            out.append(Finding(
                self.rule, path, line,
                "devicewatch program %r declares no shape_budget at any "
                "registration — the retrace sentinel can count shapes "
                "but never flag a blowout" % name,
                "program:%s" % name))
        # dedupe coercion anchors
        seen: set[str] = set()
        deduped = []
        for f in out:
            if f.key in seen:
                continue
            seen.add(f.key)
            deduped.append(f)
        return deduped


ALL_CHECKS = [TelemetryNamespace, RegisterPairing, BlockingUnderCsMain,
              LockOrder, FaultSiteParity, JitHygiene]


def all_checks():
    """The full catalog including the concurrency analysis (race.py
    imports the helpers above, so its import is deferred here to keep
    the module graph acyclic)."""
    from .race import ConcurrencyAnalysis

    return ALL_CHECKS + [ConcurrencyAnalysis]


def check_by_rule(rule: str):
    for c in all_checks():
        if c.rule == rule:
            return c
        if any(r == rule for r, _ in getattr(c, "catalog", ())):
            return c
    raise KeyError(rule)
