"""bcplint engine: module loading, check driving, baseline handling.

Findings carry a *stable key* — ``RULE path::anchor`` where the anchor
names the syntactic subject (qualname + offending name), never a line
number — so a baseline entry survives unrelated line churn in the file.

Baseline format (one entry per line)::

    BCP001 pkg/mod.py::Class.meth::flat:bcp_foo  # why this is deliberate

Every entry MUST carry a justification after `` # `` — an unjustified
entry is itself a lint failure, as is a stale entry that no longer
matches any finding (so the baseline can only shrink honestly).

Inline suppression mirrors the same contract at the line level::

    self.hits += 1  # BCPLINT-IGNORE[BCP008]: single-writer by design

An IGNORE with no justification is a failure, and an IGNORE on a line
that no longer triggers its rule is stale — also a failure (except in
``partial`` runs over a file subset, where cross-module findings are
legitimately absent).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

_IGNORE_RE = re.compile(
    r"#\s*BCPLINT-IGNORE\[(BCP\d{3})\]\s*(?::\s*(\S.*?))?\s*$")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str        # root-relative, forward slashes
    line: int
    message: str
    anchor: str      # stable subject id (no line numbers)

    @property
    def key(self) -> str:
        return "%s %s::%s" % (self.rule, self.path, self.anchor)

    def render(self) -> str:
        return "%s:%d: %s %s" % (self.path, self.line, self.rule,
                                 self.message)


class Module:
    """One parsed source file."""

    def __init__(self, root: str, abspath: str):
        self.abspath = abspath
        self.path = os.path.relpath(abspath, root).replace(os.sep, "/")
        with open(abspath, "rb") as f:
            self.source = f.read().decode("utf-8", "replace")
        self.tree = ast.parse(self.source, filename=self.path)
        # inline suppressions: (rule, line) -> justification-or-None.
        # Extracted from real COMMENT tokens, so the syntax can be
        # quoted in docstrings without registering a suppression.
        self.ignores: dict[tuple[str, int], str | None] = {}
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _IGNORE_RE.match(tok.string)
                if m:
                    self.ignores[(m.group(1), tok.start[0])] = m.group(2)
        except (tokenize.TokenError, IndentationError):
            pass


@dataclass
class LintResult:
    findings: list = field(default_factory=list)      # unbaselined Findings
    baselined: list = field(default_factory=list)     # suppressed Findings
    stale_entries: list = field(default_factory=list)      # baseline keys
    unjustified_entries: list = field(default_factory=list)
    ignored: list = field(default_factory=list)       # inline-suppressed
    stale_ignores: list = field(default_factory=list)      # "path:line RULE"
    unjustified_ignores: list = field(default_factory=list)
    errors: list = field(default_factory=list)        # (path, message)

    @property
    def ok(self) -> bool:
        return not (self.findings or self.stale_entries
                    or self.unjustified_entries or self.stale_ignores
                    or self.unjustified_ignores or self.errors)


def parse_baseline(path: str):
    """Returns (entries: dict key -> justification-or-None, order list)."""
    entries: dict[str, str | None] = {}
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if " # " in line:
                key, just = line.split(" # ", 1)
                entries[key.strip()] = just.strip() or None
            else:
                entries[line] = None
    return entries


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".pytest_cache"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def run_lint(root: str, paths=None, checks=None, baseline_path=None,
             tests_dir=None, partial=False) -> LintResult:
    """Drive ``checks`` over every .py file under ``paths`` (default: the
    package and tools trees under ``root``), apply inline IGNOREs, then
    the baseline. ``partial=True`` (the --changed mode) skips staleness
    enforcement: a subset run legitimately misses cross-module findings,
    so absent matches prove nothing."""
    from .checks import all_checks

    root = os.path.abspath(root)
    if paths is None:
        paths = [os.path.join(root, "bitcoincashplus_tpu"),
                 os.path.join(root, "tools")]
    if tests_dir is None:
        cand = os.path.join(root, "tests")
        tests_dir = cand if os.path.isdir(cand) else None

    result = LintResult()
    check_classes = checks if checks is not None else all_checks()
    instances = [c() for c in check_classes]
    ctx = {"root": root, "tests_dir": tests_dir}

    ignores: dict[str, dict] = {}  # path -> {(rule, line): just|None}
    for abspath in iter_py_files(paths):
        try:
            mod = Module(root, abspath)
        except SyntaxError as e:
            result.errors.append(
                (os.path.relpath(abspath, root), "syntax error: %s" % e))
            continue
        if mod.ignores:
            ignores[mod.path] = mod.ignores
        for check in instances:
            check.collect(mod)

    findings: list[Finding] = []
    for check in instances:
        findings.extend(check.finalize(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.anchor))

    # inline suppressions run first: they match by (path, rule, line)
    matched_ig: set[tuple[str, str, int]] = set()
    hard_findings: list[Finding] = []  # bypass the baseline
    kept: list[Finding] = []
    for f in findings:
        just = ignores.get(f.path, {}).get((f.rule, f.line), "absent")
        if just == "absent":
            kept.append(f)
            continue
        matched_ig.add((f.path, f.rule, f.line))
        if just is None:
            result.unjustified_ignores.append(
                "%s:%d %s" % (f.path, f.line, f.rule))
            hard_findings.append(f)
        else:
            result.ignored.append(f)
    findings = kept
    if not partial:
        for path in sorted(ignores):
            for (rule, line), _just in sorted(ignores[path].items(),
                                              key=lambda kv: kv[0][1]):
                if (path, rule, line) not in matched_ig:
                    result.stale_ignores.append(
                        "%s:%d %s" % (path, line, rule))

    if baseline_path and os.path.exists(baseline_path):
        entries = parse_baseline(baseline_path)
        matched: set[str] = set()
        for f in findings:
            if f.key in entries:
                matched.add(f.key)
                if entries[f.key] is None:
                    result.unjustified_entries.append(f.key)
                    result.findings.append(f)
                else:
                    result.baselined.append(f)
            else:
                result.findings.append(f)
        if not partial:
            result.stale_entries.extend(
                k for k in entries if k not in matched)
    else:
        result.findings = findings

    result.findings.extend(hard_findings)
    return result


def render_report(result: LintResult) -> str:
    out = []
    for path, msg in result.errors:
        out.append("%s: ERROR %s" % (path, msg))
    for f in result.findings:
        out.append(f.render())
    for key in result.unjustified_entries:
        out.append("baseline entry lacks a justification: %s" % key)
    for key in result.stale_entries:
        out.append("stale baseline entry (no matching finding): %s" % key)
    for key in result.unjustified_ignores:
        out.append("inline IGNORE lacks a justification: %s" % key)
    for key in result.stale_ignores:
        out.append("stale inline IGNORE (line no longer triggers): %s"
                   % key)
    if result.ok:
        out.append("bcplint: clean (%d baselined, %d inline-ignored "
                   "finding(s) justified)"
                   % (len(result.baselined), len(result.ignored)))
    else:
        out.append("bcplint: %d finding(s), %d stale, %d unjustified"
                   % (len(result.findings),
                      len(result.stale_entries)
                      + len(result.stale_ignores),
                      len(result.unjustified_entries)
                      + len(result.unjustified_ignores)))
    return "\n".join(out)
