"""bcplint engine: module loading, check driving, baseline handling.

Findings carry a *stable key* — ``RULE path::anchor`` where the anchor
names the syntactic subject (qualname + offending name), never a line
number — so a baseline entry survives unrelated line churn in the file.

Baseline format (one entry per line)::

    BCP001 pkg/mod.py::Class.meth::flat:bcp_foo  # why this is deliberate

Every entry MUST carry a justification after `` # `` — an unjustified
entry is itself a lint failure, as is a stale entry that no longer
matches any finding (so the baseline can only shrink honestly).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str        # root-relative, forward slashes
    line: int
    message: str
    anchor: str      # stable subject id (no line numbers)

    @property
    def key(self) -> str:
        return "%s %s::%s" % (self.rule, self.path, self.anchor)

    def render(self) -> str:
        return "%s:%d: %s %s" % (self.path, self.line, self.rule,
                                 self.message)


class Module:
    """One parsed source file."""

    def __init__(self, root: str, abspath: str):
        self.abspath = abspath
        self.path = os.path.relpath(abspath, root).replace(os.sep, "/")
        with open(abspath, "rb") as f:
            self.source = f.read().decode("utf-8", "replace")
        self.tree = ast.parse(self.source, filename=self.path)


@dataclass
class LintResult:
    findings: list = field(default_factory=list)      # unbaselined Findings
    baselined: list = field(default_factory=list)     # suppressed Findings
    stale_entries: list = field(default_factory=list)      # baseline keys
    unjustified_entries: list = field(default_factory=list)
    errors: list = field(default_factory=list)        # (path, message)

    @property
    def ok(self) -> bool:
        return not (self.findings or self.stale_entries
                    or self.unjustified_entries or self.errors)


def parse_baseline(path: str):
    """Returns (entries: dict key -> justification-or-None, order list)."""
    entries: dict[str, str | None] = {}
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if " # " in line:
                key, just = line.split(" # ", 1)
                entries[key.strip()] = just.strip() or None
            else:
                entries[line] = None
    return entries


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".pytest_cache"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def run_lint(root: str, paths=None, checks=None, baseline_path=None,
             tests_dir=None) -> LintResult:
    """Drive ``checks`` over every .py file under ``paths`` (default: the
    package and tools trees under ``root``), then apply the baseline."""
    from .checks import ALL_CHECKS

    root = os.path.abspath(root)
    if paths is None:
        paths = [os.path.join(root, "bitcoincashplus_tpu"),
                 os.path.join(root, "tools")]
    if tests_dir is None:
        cand = os.path.join(root, "tests")
        tests_dir = cand if os.path.isdir(cand) else None

    result = LintResult()
    check_classes = checks if checks is not None else ALL_CHECKS
    instances = [c() for c in check_classes]
    ctx = {"root": root, "tests_dir": tests_dir}

    for abspath in iter_py_files(paths):
        try:
            mod = Module(root, abspath)
        except SyntaxError as e:
            result.errors.append(
                (os.path.relpath(abspath, root), "syntax error: %s" % e))
            continue
        for check in instances:
            check.collect(mod)

    findings: list[Finding] = []
    for check in instances:
        findings.extend(check.finalize(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.anchor))

    if baseline_path and os.path.exists(baseline_path):
        entries = parse_baseline(baseline_path)
        matched: set[str] = set()
        for f in findings:
            if f.key in entries:
                matched.add(f.key)
                if entries[f.key] is None:
                    result.unjustified_entries.append(f.key)
                    result.findings.append(f)
                else:
                    result.baselined.append(f)
            else:
                result.findings.append(f)
        result.stale_entries.extend(
            k for k in entries if k not in matched)
    else:
        result.findings = findings

    return result


def render_report(result: LintResult) -> str:
    out = []
    for path, msg in result.errors:
        out.append("%s: ERROR %s" % (path, msg))
    for f in result.findings:
        out.append(f.render())
    for key in result.unjustified_entries:
        out.append("baseline entry lacks a justification: %s" % key)
    for key in result.stale_entries:
        out.append("stale baseline entry (no matching finding): %s" % key)
    if result.ok:
        out.append("bcplint: clean (%d baselined finding(s) justified)"
                   % len(result.baselined))
    else:
        out.append("bcplint: %d finding(s), %d stale, %d unjustified"
                   % (len(result.findings), len(result.stale_entries),
                      len(result.unjustified_entries)))
    return "\n".join(out)
