"""SHA-256d roofline evidence generator (see ROOFLINE.md for the analysis).

Three measurements, run on the real chip (axon / TPU v5e-lite):

  1. op census   — count the (tile,)-shaped vector ops per nonce in the
                   traced kernels (jaxpr walk). This is the op count the VPU
                   actually executes; scalar/host-folded work is excluded.
  2. op probe    — sustained u32 elementwise throughput on dependency
                   chains of SHA-like op mixes, measured MARGINALLY (two
                   loop lengths, delta-work / delta-time) so the ~200ms
                   tunnel round-trip cancels out.
  3. achieved    — the tuned Pallas sweep's GH/s, converted to executed
                   vector-ops/s via the census.

Peak reference: v5e TensorCore VPU = (8,128) lanes x 4 ALUs; clock derived
from the published 197.4 Tbf16FLOP/s over 4 MXUs of 128x128 MACs
(= 1.506 GHz) -> 6.17e12 u32 op/s theoretical ceiling.

Usage: python tools/roofline.py   (needs the TPU; ~3 min)
"""

from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# --ecdsa: trace-only ECDSA vector-op census (w4 vs GLV kernels) — no
# device needed, and the accelerator plugin must not wedge a CPU-only
# tool run, so pin the backend BEFORE jax imports. BCP_SECP_PARALLEL=1
# traces the parallel field forms — the ops the device VPU executes —
# rather than the CPU backend's compile-friendly scan forms.
# --mining: sweep-kernel census (generic vs chunk-2-hoisted, ISSUE 10)
# plus the live compiled-flops drift check of the resident miner program;
# CPU-pinned the same way.
ECDSA_MODE = "--ecdsa" in sys.argv
MINING_MODE = "--mining" in sys.argv
if ECDSA_MODE or MINING_MODE:
    os.environ["JAX_PLATFORMS"] = "cpu"
if ECDSA_MODE:
    os.environ["BCP_SECP_PARALLEL"] = "1"

import jax
import jax.numpy as jnp
import numpy as np

from bitcoincashplus_tpu.crypto.hashes import header_midstate
from bitcoincashplus_tpu.ops import sha256 as gen
from bitcoincashplus_tpu.ops.sha256 import bswap32, bytes_to_words_np
from bitcoincashplus_tpu.ops.sha256_sweep import sweep_h7

VPU_PEAK_OPS = 8 * 128 * 4 * 1.506e9  # lanes x ALUs x clock = 6.17e12

HEADER = bytes(range(80))
MID = list(np.array(header_midstate(HEADER), dtype=np.uint32))
TAIL = list(bytes_to_words_np(np.frombuffer(HEADER[64:76], np.uint8)))


# ---- 1. vector-op census ----------------------------------------------------

def census(f, *args, tile=1024):
    jaxpr = jax.make_jaxpr(f)(*args)
    counts: dict[str, int] = {}

    def walk(jx):
        for eqn in jx.eqns:
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)
            shapes = [v.aval.shape for v in eqn.outvars if hasattr(v.aval, "shape")]
            if any(s and int(np.prod(s)) >= tile for s in shapes):
                counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1

    walk(jaxpr.jaxpr)
    return counts


def run_census():
    from bitcoincashplus_tpu.ops.sha256_sweep import (
        hoist_template,
        sweep_digest_hoisted,
    )

    nonces = jnp.zeros((1024,), jnp.uint32)
    # sweep_h7 routes through hoist_template since ISSUE 10 — this IS the
    # post-hoist h7 count (pre-hoist was 5923; see ROOFLINE.md §8)
    spec = census(lambda n: sweep_h7(MID, TAIL, n), nonces)

    unroll_save = os.environ.get("BCP_SHA_UNROLL")
    os.environ["BCP_SHA_UNROLL"] = "1"

    def generic(n):
        h8 = gen.header_sweep_digest(
            [np.uint32(m) for m in MID], [np.uint32(t) for t in TAIL], n
        )
        return gen.le256(gen.digest_to_limbs(h8), [np.uint32(0)] * 8)

    def hoisted_full(n):
        h8 = sweep_digest_hoisted(hoist_template(MID, TAIL), n)
        return gen.le256(gen.digest_to_limbs(h8), [np.uint32(0)] * 8)

    full = census(generic, nonces)
    hoisted = census(hoisted_full, nonces)
    if unroll_save is None:
        os.environ.pop("BCP_SHA_UNROLL", None)
    else:
        os.environ["BCP_SHA_UNROLL"] = unroll_save
    return sum(spec.values()), sum(full.values()), sum(hoisted.values()), spec


# ---- 2. sustained-op probe --------------------------------------------------

def _rotr(x, n):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


PROBE_MIXES = {
    # naive op counting convention: rotr = 3 ops (2 shifts + or)
    "sigma": (lambda x, c: (_rotr(x, 2) ^ _rotr(x, 13) ^ _rotr(x, 22)) + c, 12),
    "ch": (lambda x, c: ((x & c) ^ (~x & _rotr(x, 6))) + c, 8),
    "addrot": (lambda x, c: (x + c) ^ _rotr(x, 7), 5),
}

PROBE_N = 1 << 20
PROBE_INNER = 256


def _probe_fn(body, outer):
    @jax.jit
    def f(x):
        def o(i, x):
            c0 = i.astype(jnp.uint32) * np.uint32(0x9E3779B9)
            for j in range(PROBE_INNER):
                x = body(x, c0 + np.uint32(j))
            return x
        return jax.lax.fori_loop(0, outer, o, x)[0]
    return f


def _timed(f):
    rng = np.random.default_rng(0)
    _ = int(f(jnp.asarray(rng.integers(0, 2**32, PROBE_N, dtype=np.uint32))))
    ts = []
    for _i in range(3):
        x = jnp.asarray(rng.integers(0, 2**32, PROBE_N, dtype=np.uint32))
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        _ = int(f(x))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[1]


def run_probe():
    out = {}
    for name, (body, ops) in PROBE_MIXES.items():
        t_lo = _timed(_probe_fn(body, 32))
        t_hi = _timed(_probe_fn(body, 288))
        dwork = PROBE_N * PROBE_INNER * (288 - 32) * ops
        out[name] = dwork / (t_hi - t_lo)
    return out


# ---- 3. achieved sweep rate -------------------------------------------------

def run_sweep_rate(sublanes=64, max_tiles=262144):
    from bitcoincashplus_tpu.ops.pallas_sweep import pallas_sweep_jit

    mid = jnp.asarray(np.array(MID, dtype=np.uint32))
    tail = jnp.asarray(np.array(TAIL, dtype=np.uint32))
    t7 = jnp.uint32(0)
    tile = sublanes * 128

    def f(s, n):
        return pallas_sweep_jit(mid, tail, t7, s, n,
                                sublanes=sublanes, max_tiles=max_tiles)

    r = f(jnp.uint32(0), jnp.uint32(1))
    _ = int(r[2])
    rates = []
    for _i in range(4):
        t0 = time.perf_counter()
        out = f(jnp.uint32(random.getrandbits(32)), jnp.uint32(max_tiles))
        tiles = int(out[2])
        rates.append(tiles * tile / (time.perf_counter() - t0))
    return sorted(rates[1:])[len(rates[1:]) // 2]


# ---- ECDSA vector-op census (--ecdsa) ---------------------------------------
#
# Counts the lane-shaped vector ops per verify for the w4 and GLV kernels
# by tracing each kernel PHASE separately (table build, ladder window,
# comb tooth, final check) and scaling by its trip count — the cores run
# their windows under lax.fori_loop, whose body a plain jaxpr walk counts
# once. Same counting convention as the SHA census: only ops whose output
# carries the lane axis; scalar/host work is excluded.

def _ecdsa_census_parts(B: int = 128):
    import jax.numpy as jnp

    from bitcoincashplus_tpu.crypto import secp256k1 as orc
    from bitcoincashplus_tpu.ops import secp256k1 as S

    rng = random.Random(9)

    def limbs():
        return jnp.asarray(
            S.pack_batch_np([rng.randrange(orc.P) for _ in range(B)])
        )

    qx, qy, r0, rn = limbs(), limbs(), limbs(), limbs()
    one = jnp.asarray(
        np.broadcast_to(S.to_limbs_np(1).reshape(S.N_LIMBS, 1), (S.N_LIMBS, B))
    ).astype(jnp.uint32)
    q_inf_u = jnp.zeros((1, B), jnp.int32)
    never_inf = jnp.zeros((1, B), jnp.int32)
    wrap2 = jnp.zeros((1, B), jnp.uint32)
    win = jnp.ones((1, B), jnp.int32) * 7
    acc = {"X": qx, "Y": qy, "Z": qx, "inf": jnp.zeros((1, B), jnp.int32)}
    degen = jnp.zeros((1, B), jnp.int32)
    shape = (S.N_LIMBS, B)

    def count(f, *args):
        jaxpr = jax.make_jaxpr(f)(*args)
        total = 0

        def walk(jx):
            nonlocal total
            for eqn in jx.eqns:
                for sub in eqn.params.values():
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr)
                shapes = [v.aval.shape for v in eqn.outvars
                          if hasattr(v.aval, "shape")]
                if any(s and int(np.prod(s)) >= B for s in shapes):
                    total += 1

        walk(jaxpr.jaxpr)
        return total

    # w4 phases
    w4_tables = count(
        lambda qx, qy: S._w4_tables(qx, qy, q_inf_u, one, shape)[1], qx, qy
    )

    def w4_step(qx, qy, acc_in):
        g_tab, q_tab = S._w4_tables(qx, qy, q_inf_u, one, shape)
        return S._w4_window_step((acc_in, degen), win, win, g_tab, q_tab,
                                 q_inf_u, one, never_inf)

    w4_window = count(w4_step, qx, qy, acc) - count(
        lambda qx, qy: S._w4_tables(qx, qy, q_inf_u, one, shape), qx, qy
    )
    w4_final = count(
        lambda a, r0, rn: S._verify_final(a, degen, q_inf_u, r0, rn, wrap2),
        acc, r0, rn,
    )

    # GLV phases
    glv_tables = count(
        lambda qx, qy: S._glv_q_tables(qx, qy, q_inf_u * 0, q_inf_u, one),
        qx, qy,
    )

    def glv_step(qx, qy, acc_in):
        t1, t2 = S._glv_q_tables(qx, qy, q_inf_u * 0, q_inf_u, one)
        return S._glv_window_step((acc_in, degen), win, win, t1, t2, q_inf_u)

    glv_window = count(glv_step, qx, qy, acc) - glv_tables
    # device-side lattice decomposition (ISSUE 11): per-scalar cost of
    # the in-kernel split (limb-expand + exact rounding + magnitude
    # emission); a fused verify pays it twice (u1 and u2) plus the
    # window/digit planes — all O(1) per lane against the ladder
    km8 = jnp.zeros((B, 32), jnp.uint8)
    glv_decompose = count(
        lambda m: S._glv_split_device(S._expand_limb_cols(m)), km8)
    glv_emit = count(
        lambda m: (
            S._bits_to_comb_digits(
                S._mag_bits128(S._expand_limb_cols(m)[:10])),
            S._bits_to_nibble_windows(
                S._mag_bits128(S._expand_limb_cols(m)[:10])),
        ),
        km8)
    comb = S._glv_comb()
    tab_x = jnp.asarray(comb[0][0])
    tab_y = jnp.asarray(comb[1][0])
    drow = jnp.ones((B,), jnp.int32) * 9
    sgrow = jnp.zeros((B,), jnp.int32)
    glv_tooth = count(
        lambda a: S._glv_comb_step((a, degen), drow, sgrow, tab_x, tab_y,
                                   one, never_inf),
        acc,
    )
    glv_final = w4_final  # shared epilogue (_verify_final)

    w4_total = w4_tables + 64 * w4_window + w4_final
    glv_total = (glv_tables + S.GLV_WINDOWS * glv_window
                 + 2 * S.GLV_COMB_TEETH * glv_tooth + glv_final)
    return {
        "w4": {"tables": w4_tables, "window": w4_window, "windows": 64,
               "final": w4_final, "total": w4_total},
        "glv": {"tables": glv_tables, "window": glv_window,
                "windows": S.GLV_WINDOWS, "comb_tooth": glv_tooth,
                "comb_adds": 2 * S.GLV_COMB_TEETH, "final": glv_final,
                "total": glv_total,
                # the fused device-decompose program's extra per-lane
                # cost: two splits (u1, u2) + the magnitude plane emits
                "decompose_per_scalar": glv_decompose,
                "decompose_emit": glv_emit,
                "decompose_total": 2 * (glv_decompose + glv_emit),
                "total_with_decompose":
                    glv_total + 2 * (glv_decompose + glv_emit)},
    }


def run_ecdsa_census():
    parts = _ecdsa_census_parts()
    w4, glv = parts["w4"], parts["glv"]
    print("ECDSA verify kernels — vector ops per lane "
          "(parallel field forms, jaxpr census)")
    print(f"{'phase':<28}{'w4':>12}{'glv':>12}")
    print(f"{'table build (per batch)':<28}{w4['tables']:>12,}"
          f"{glv['tables']:>12,}")
    print(f"{'ladder window (each)':<28}{w4['window']:>12,}"
          f"{glv['window']:>12,}")
    print(f"{'ladder windows':<28}{w4['windows']:>12}{glv['windows']:>12}")
    print(f"{'comb tooth (each)':<28}{'-':>12}{glv['comb_tooth']:>12,}")
    print(f"{'comb adds':<28}{'-':>12}{glv['comb_adds']:>12}")
    print(f"{'final check':<28}{w4['final']:>12,}{glv['final']:>12,}")
    print(f"{'TOTAL per verify':<28}{w4['total']:>12,}{glv['total']:>12,}")
    red = 1.0 - glv['total'] / w4['total']
    print(f"GLV reduction vs w4: {red * 100:.1f}% "
          f"({'meets' if red >= 0.30 else 'MISSES'} the >=30% target)")
    print("\ndevice-side decompose census (ISSUE 11, per lane):")
    print(f"{'split (per scalar)':<28}{glv['decompose_per_scalar']:>12,}")
    print(f"{'plane emit (per scalar)':<28}{glv['decompose_emit']:>12,}")
    print(f"{'decompose total (x2)':<28}{glv['decompose_total']:>12,}")
    oh = glv['decompose_total'] / glv['total']
    print(f"{'fused verify total':<28}"
          f"{glv['total_with_decompose']:>12,}  "
          f"(+{oh * 100:.2f}% over the ladder — the host leg it "
          "replaces was 56% of wall)")
    return parts


# ---- Schnorr MSM census (--ecdsa, ISSUE 19) ---------------------------------
#
# The Pippenger bucket-accumulation program (ops/secp256k1._msm_accumulate)
# amortizes ONE batch equation over M terms (M = 2·sigs + 1), so its unit
# is vector ops per TERM, not per verify lane. Same phase-and-scale
# convention as the w4/GLV census: each loop body is traced once and
# multiplied by its trip count. Census shape M = 64 (the test/drill rung:
# K = 2 streams x 32 steps); the per-term number is K-independent because
# a step always processes K terms across K·64 lanes.

def _msm_census_parts(M: int = 64):
    import jax.numpy as jnp

    from bitcoincashplus_tpu.crypto import secp256k1 as orc
    from bitcoincashplus_tpu.ops import secp256k1 as S

    rng = random.Random(9)
    K = max(1, min(128, M // 32))
    steps = M // K
    lanes = K * 64

    def count(f, *args, floor=64):
        """Vector ops whose output carries >= ``floor`` elements (the MSM
        reduction phases run at width 64; the Horner epilogue runs at
        width 1 and is counted with floor=1 — see below)."""
        jaxpr = jax.make_jaxpr(f)(*args)
        total = 0

        def walk(jx):
            nonlocal total
            for eqn in jx.eqns:
                for sub in eqn.params.values():
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr)
                shapes = [v.aval.shape for v in eqn.outvars
                          if hasattr(v.aval, "shape")]
                if any(s and int(np.prod(s)) >= floor for s in shapes):
                    total += 1

        walk(jaxpr.jaxpr)
        return total

    def limbs(width):
        return jnp.asarray(S.pack_batch_np(
            [rng.randrange(orc.P) for _ in range(width)]))

    # step phase: bucket gather + complete mixed add + one-hot scatter,
    # emulated at the real (lanes, 16) bucket shape
    bk = {"X": jnp.ones((S.N_LIMBS, lanes, 16), jnp.uint32),
          "Y": jnp.ones((S.N_LIMBS, lanes, 16), jnp.uint32),
          "Z": jnp.zeros((S.N_LIMBS, lanes, 16), jnp.uint32),
          "inf": jnp.ones((lanes, 16), bool)}
    d = jnp.ones((lanes,), jnp.int32) * 7
    qx, qy = limbs(lanes), limbs(lanes)
    qi = jnp.zeros((lanes,), bool)
    bucket_ids = jnp.arange(16, dtype=jnp.int32)

    def step_body(bk, qx, qy):
        cur = {
            "X": jnp.take_along_axis(bk["X"], d[None, :, None],
                                     axis=2)[..., 0],
            "Y": jnp.take_along_axis(bk["Y"], d[None, :, None],
                                     axis=2)[..., 0],
            "Z": jnp.take_along_axis(bk["Z"], d[None, :, None],
                                     axis=2)[..., 0],
            "inf": jnp.take_along_axis(bk["inf"], d[:, None],
                                       axis=1)[:, 0],
        }
        new = S.pt_add_mixed(cur, qx, qy, qi)
        hit = (bucket_ids[None, :] == d[:, None]) & ((d > 0) & ~qi)[:, None]
        return {
            "X": jnp.where(hit[None], new["X"][:, :, None], bk["X"]),
            "Y": jnp.where(hit[None], new["Y"][:, :, None], bk["Y"]),
            "Z": jnp.where(hit[None], new["Z"][:, :, None], bk["Z"]),
            "inf": jnp.where(hit, new["inf"][:, None], bk["inf"]),
        }

    step = count(step_body, bk, qx, qy, floor=lanes)

    # merge / reduction phases: one COMPLETE Jacobian add each (the jaxpr
    # op count of pt_add_full is width-independent; widths halve down the
    # merge tree and sit at 64 through the bucket reduction)
    w = 64
    pt_a = {"X": limbs(w), "Y": limbs(w), "Z": limbs(w),
            "inf": jnp.zeros((w,), bool)}
    pt_b = {"X": limbs(w), "Y": limbs(w), "Z": limbs(w),
            "inf": jnp.zeros((w,), bool)}
    full_add = count(S.pt_add_full, pt_a, pt_b, floor=w)
    merge_levels = int(np.log2(K)) if K > 1 else 0
    # suffix running sums: running += B_b; total += running  (2 adds x 15)
    red = 2 * full_add

    # Horner epilogue at width 1: 64 x (4 doubles + 1 add) — counted with
    # floor=1 (every op is a (20, 1) vector op on device; excluded from
    # the >=64-wide phases above by the same rule that excludes scalar
    # work from the SHA census)
    pt_1 = {"X": limbs(1), "Y": limbs(1), "Z": limbs(1),
            "inf": jnp.zeros((1,), bool)}
    horner = count(
        lambda a, b: S.pt_add_full(S.pt_double(S.pt_double(S.pt_double(
            S.pt_double(a)))), b), pt_1, pt_1, floor=1)

    total = (steps * step + merge_levels * full_add + 15 * red
             + 64 * horner)
    return {
        "M": M, "K": K, "steps": steps, "lanes": lanes,
        "step": step, "full_add": full_add, "merge_levels": merge_levels,
        "reduction": 15 * red, "horner": 64 * horner,
        "total": total, "per_term": total / M,
    }


def run_msm_census():
    p = _msm_census_parts()
    print(f"\nSchnorr MSM bucket accumulation — vector ops "
          f"(M = {p['M']} terms: K = {p['K']} streams x {p['steps']} "
          f"steps, {p['lanes']} window lanes)")
    print(f"{'phase':<34}{'ops':>12}")
    print(f"{'bucket step (each)':<34}{p['step']:>12,}")
    print(f"{'bucket steps':<34}{p['steps']:>12}")
    print(f"{'stream merge (full adds)':<34}{p['merge_levels']:>12}")
    print(f"{'bucket reduction (15 rounds)':<34}{p['reduction']:>12,}")
    print(f"{'Horner epilogue (64 windows)':<34}{p['horner']:>12,}")
    print(f"{'TOTAL per batch equation':<34}{p['total']:>12,}")
    print(f"{'amortized per term':<34}{p['per_term']:>12,.1f}")
    return p


# ---- live cost-analysis drift check (--ecdsa) -------------------------------
#
# The static jaxpr census above is a MODEL derived from a specific kernel
# + compiler state; the compiled executable's own cost_analysis() is what
# XLA actually admitted to for the SAME state, recorded below as the
# census's compiled twin. The units are not cross-comparable (census =
# lane-shaped primitives of the kernel cores; cost_analysis = element
# flops of the whole lowered program — the w4 path additionally lowers
# through pallas interpret on CPU), so drift is per kernel against its
# OWN recorded baseline: a live compiled-flops number that moved > 10%
# from the baseline means a kernel or compiler change shifted the real
# op mix and BOTH the census and these baselines must be re-derived.
#
# This drives one real dispatch per kernel through the util/devicewatch
# program registry (BCP_DEVICEWATCH_COST=always captures cost_analysis
# at first compile into the SAME "ecdsa_glv"/"ecdsa_w4_bytes" programs a
# running node populates — the live registry, not a side channel).

DRIFT_BUDGET = 0.10

# compiled flops/lane at bucket 1024, recorded when the §7 census was
# last validated (jax 0.4.37). Keyed by the lowering arrangement — the
# CPU arrangement is plain-XLA GLV + pallas-INTERPRET w4; a Mosaic (TPU)
# run lowers differently and reports without flagging until a baseline
# for that arrangement is recorded here.
COST_BASELINES = {
    "cpu": {"ecdsa_glv": 2_370_312.0, "ecdsa_w4_bytes": 1_618_602.0,
            # the fused decompose+verify program (ISSUE 11) — the
            # parallel-form lowering's whole-program flop accounting
            # weighs the unrolled carry rounds far above their census
            # primitive count (+12.6k census vs +1.19M flops), which is
            # exactly why drift is per kernel against its OWN twin
            "ecdsa_glv_decompose": 3_562_004.0,
            # Schnorr MSM batch check (ISSUE 19): compiled flops per
            # TERM-SLOT at bucket 64 (the whole batch-equation program's
            # flop count / 64 slots — the smallest, unit-test-priced
            # rung; bigger buckets amortize the fixed Horner epilogue so
            # their per-slot number is NOT comparable). §10's census
            # counts 21.1k primitives/term at this shape — same units
            # caveat as the fused decompose twin above
            "ecdsa_msm": 3_716_708.0,
            # miner_resident compiled flops/nonce at tile 1024 (exact =
            # looped-compress lowering — the form a CPU backend compiles;
            # h7 = the fully-unrolled trace, which XLA's whole-program
            # flop accounting weighs differently — hence per-kernel
            # baselines), recorded when the §8 post-hoist census was
            # validated (jax 0.4.37) — the census's compiled twin for
            # the mining drift check
            "miner_resident_exact": 6_244.4,
            "miner_resident_h7": 11_791.4},
}


def run_ecdsa_live_drift(parts, bucket: int = 1024):
    os.environ["BCP_DEVICEWATCH_COST"] = "always"
    import tempfile

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(tempfile.gettempdir(), "bcp-jax-test-cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

    from bitcoincashplus_tpu.crypto import secp256k1 as orc
    from bitcoincashplus_tpu.ops import ecdsa_batch as eb
    from bitcoincashplus_tpu.ops import secp256k1 as S
    from bitcoincashplus_tpu.ops.sha256 import backend_is_cpu
    from bitcoincashplus_tpu.script.interpreter import SigCheckRecord
    from bitcoincashplus_tpu.util import devicewatch as dwatch

    rng = random.Random(17)
    records = []
    for _ in range(4):
        sk = rng.randrange(1, orc.N)
        e = rng.getrandbits(256) % orc.N
        r, s = orc.ecdsa_sign(sk, e)
        records.append(SigCheckRecord(orc.point_mul(sk, orc.G), r, s, e))

    print(f"\nlive cost-analysis drift check (bucket {bucket}, one real "
          "dispatch per kernel through the devicewatch registry)...")
    glv_args = eb.pack_records_glv(records, bucket)
    with dwatch.program("ecdsa_glv").dispatch(
            bucket, jitfn=S._glv_program, args=glv_args):
        jax.block_until_ready(S._glv_program(*glv_args))
    dev_args = eb.pack_records_w4_bytes(records, bucket)
    with dwatch.program("ecdsa_glv_decompose").dispatch(
            bucket, jitfn=S._glv_dev_program, args=dev_args):
        jax.block_until_ready(S._glv_dev_program(*dev_args))
    interp = backend_is_cpu()
    w4_args = eb.pack_records_w4_bytes(records, bucket)
    with dwatch.program("ecdsa_w4_bytes").dispatch(
            bucket, jitfn=S._w4_bytes_program, args=w4_args,
            kwargs={"interpret": interp}):
        jax.block_until_ready(
            S._w4_bytes_program(*w4_args, interpret=interp))
    # Schnorr MSM batch-equation program (ISSUE 19) at ITS census rung —
    # bucket 64, the smallest _MSM_BUCKETS shape (1024 is a many-minute
    # XLA compile on a CPU backend; the flops/term-slot unit is bucket-
    # normalized either way). One canary-sized batch through the real
    # dispatch helper populates the same "ecdsa_msm" watch a node feeds.
    msm_bucket = 64
    kg, kb = eb._schnorr_kat_records()
    eb._msm_device_check(
        [(kg, eb._schnorr_precheck(kg)), (kb, eb._schnorr_precheck(kb))],
        random.Random(17))

    progs = dwatch.snapshot()["programs"]
    per_name_bucket = {"ecdsa_glv": bucket, "ecdsa_glv_decompose": bucket,
                       "ecdsa_w4_bytes": bucket, "ecdsa_msm": msm_bucket}
    live = {}
    for name, bkt in per_name_bucket.items():
        cost = progs.get(name, {}).get("cost", {}).get(str((bkt,)))
        if not cost:
            print("live drift check: cost_analysis unavailable on this "
                  "backend — skipped")
            return None
        live[name] = cost["flops"] / bkt

    arrangement = "cpu" if interp else "mosaic"
    baselines = COST_BASELINES.get(arrangement)
    census_ratio = parts["glv"]["total"] / parts["w4"]["total"]
    print(f"{'':<28}{'w4':>14}{'glv':>14}{'glv+dec':>14}")
    print(f"{'census ops/lane':<28}{parts['w4']['total']:>14,}"
          f"{parts['glv']['total']:>14,}"
          f"{parts['glv']['total_with_decompose']:>14,}")
    print(f"{'compiled flops/lane':<28}{live['ecdsa_w4_bytes']:>14,.0f}"
          f"{live['ecdsa_glv']:>14,.0f}"
          f"{live['ecdsa_glv_decompose']:>14,.0f}")
    print(f"census glv/w4 ratio: {census_ratio:.4f} "
          "(primitive counts of the kernel cores — see §7)")
    print(f"msm compiled flops/term-slot (bucket {msm_bucket}): "
          f"{live['ecdsa_msm']:>14,.0f}")
    if baselines is None:
        print(f"no compiled-cost baseline recorded for the "
              f"{arrangement!r} lowering arrangement — reporting only "
              "(record one in COST_BASELINES to arm the drift flag)")
        return {"live": live, "drift": None, "ok": None}
    out = {"live": live, "ok": True}
    for name, base in baselines.items():
        if name not in live:
            continue  # other tools' baselines (miner_resident_*) share
            # the arrangement dict — only compare what THIS check ran
        drift = abs(live[name] - base) / base
        flagged = drift > DRIFT_BUDGET
        out[name] = {"baseline": base, "live": live[name], "drift": drift}
        out["ok"] = out["ok"] and not flagged
        verdict = ("DRIFT EXCEEDS BUDGET — a kernel/compiler change "
                   "moved the real op mix; re-derive the §7 census AND "
                   "this baseline") if flagged else "within budget"
        print(f"{name}: live {live[name]:,.0f} vs baseline {base:,.0f} "
              f"flops/lane — drift {drift * 100:.1f}% "
              f"(budget {DRIFT_BUDGET * 100:.0f}%) — {verdict}")
    for name in live:
        if name not in baselines:
            print(f"{name}: live {live[name]:,.0f} flops/lane — no "
                  "baseline recorded for this arrangement yet (record "
                  "one in COST_BASELINES to arm the drift flag)")
            out["ok"] = None if out["ok"] is True else out["ok"]
    return out


# ---- mining sweep census + live drift (--mining) ----------------------------
#
# The ISSUE 10 twin of the ECDSA section: the chunk-2 hoist's ops/nonce
# claim (ROOFLINE.md §8) as a re-runnable census, plus the compiled-flops
# drift check of the resident miner program. One real dispatch per kernel
# goes through the SAME devicewatch program a running node populates
# ("miner_resident", sig = (kernel, tile)); cost_analysis at first
# compile is compared per kernel against its recorded baseline — the
# units (census primitive counts vs whole-program element flops, body of
# the while_loop counted once) are not cross-comparable, so drift is
# per kernel against its OWN compiled twin, flagged at > 10%.

PRE_HOIST_H7 = 5923      # ops/nonce before the chunk-2 hoist (§2)
PRE_HOIST_FULL = 7041    # generic full-digest sweep, unhoisted (§2)


def run_mining_census():
    spec_ops, full_ops, hoisted_full_ops, _detail = run_census()
    print("nonce-sweep kernels — vector ops per nonce (jaxpr census)")
    print(f"{'kernel':<42}{'ops/nonce':>12}")
    print(f"{'generic full-digest (unhoisted)':<42}{full_ops:>12,}")
    print(f"{'full-digest + chunk-2 hoist (resident exact)':<42}"
          f"{hoisted_full_ops:>12,}")
    print(f"{'truncated-h7, pre-hoist (r10 baseline)':<42}"
          f"{PRE_HOIST_H7:>12,}")
    print(f"{'truncated-h7 + chunk-2 hoist':<42}{spec_ops:>12,}")
    red = 1.0 - spec_ops / PRE_HOIST_H7
    print(f"chunk-2 hoist reduction vs pre-hoist h7: {red * 100:.2f}% "
          f"({'below' if spec_ops < PRE_HOIST_H7 else 'NOT below'} "
          f"the 5923 baseline)")
    return {"h7_hoisted": spec_ops, "full_generic": full_ops,
            "full_hoisted": hoisted_full_ops,
            "h7_pre_hoist": PRE_HOIST_H7}


def run_mining_live_drift(census_d, tile: int = 1024):
    os.environ["BCP_DEVICEWATCH_COST"] = "always"
    import tempfile

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(tempfile.gettempdir(), "bcp-jax-test-cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

    from bitcoincashplus_tpu.mining.resident import (
        PROGRAM,
        SHAPE_BUDGET,
        ResidentSweep,
    )
    from bitcoincashplus_tpu.ops.sha256 import backend_is_cpu
    from bitcoincashplus_tpu.util import devicewatch as dwatch

    print(f"\nlive cost-analysis drift check (tile {tile}, one real "
          "segment dispatch per kernel through the devicewatch "
          f"{PROGRAM!r} program, shape budget {SHAPE_BUDGET})...")
    header = HEADER
    target = 0  # impossible: the segment runs its full tile
    live = {}
    for kernel in ("exact", "h7"):
        rs = ResidentSweep(tile=tile, seg_tiles=1, inflight=1,
                           kernel=kernel)
        rs.sweep(header, target, max_nonces=tile)
        rs.close()
        snap = dwatch.program(PROGRAM).snapshot()
        cost = snap["cost"].get(str((kernel, tile)))
        if not cost:
            print("live drift check: cost_analysis unavailable on this "
                  "backend — skipped")
            return None
        live[f"miner_resident_{kernel}"] = cost["flops"] / tile
    arrangement = "cpu" if backend_is_cpu() else "mosaic"
    baselines = COST_BASELINES.get(arrangement, {})
    print(f"{'kernel':<28}{'census ops/nonce':>18}{'flops/nonce':>16}")
    print(f"{'exact (full digest)':<28}{census_d['full_hoisted']:>18,}"
          f"{live['miner_resident_exact']:>16,.1f}")
    print(f"{'h7 (truncated)':<28}{census_d['h7_hoisted']:>18,}"
          f"{live['miner_resident_h7']:>16,.1f}")
    out = {"live": live, "ok": True}
    for name, val in live.items():
        base = baselines.get(name)
        if base is None:
            print(f"{name}: live {val:,.1f} flops/nonce — no baseline "
                  f"recorded for the {arrangement!r} arrangement "
                  "(record one in COST_BASELINES to arm the drift flag)")
            out["ok"] = None
            continue
        drift = abs(val - base) / base
        flagged = drift > DRIFT_BUDGET
        out[name] = {"baseline": base, "live": val, "drift": drift}
        if out["ok"] is not None:
            out["ok"] = out["ok"] and not flagged
        verdict = ("DRIFT EXCEEDS BUDGET — a kernel/compiler change "
                   "moved the real op mix; re-derive the §8 census AND "
                   "this baseline") if flagged else "within budget"
        print(f"{name}: live {val:,.1f} vs baseline {base:,.1f} "
              f"flops/nonce — drift {drift * 100:.1f}% "
              f"(budget {DRIFT_BUDGET * 100:.0f}%) — {verdict}")
    return out


def main():
    if ECDSA_MODE:
        parts = run_ecdsa_census()
        run_msm_census()
        run_ecdsa_live_drift(parts)
        return
    if MINING_MODE:
        census_d = run_mining_census()
        run_mining_live_drift(census_d)
        return
    spec_ops, full_ops, hoisted_full_ops, spec_detail = run_census()
    print(f"census: specialized h7 sweep = {spec_ops} vector ops/nonce "
          f"(chunk-2 hoisted; pre-hoist {PRE_HOIST_H7})")
    print(f"census: generic full-digest  = {full_ops} vector ops/nonce "
          f"(hoisted full-digest: {hoisted_full_ops})")
    print(f"census detail: {spec_detail}")

    on_tpu = jax.default_backend() != "cpu"
    if not on_tpu:
        print("(CPU backend: skipping device measurements)")
        return

    probe = run_probe()
    for name, rate in probe.items():
        print(f"probe {name}: {rate/1e12:.2f} T u32-ops/s sustained (naive count)")

    ghs = run_sweep_rate() / 1e9
    achieved_ops = ghs * 1e9 * spec_ops
    print(f"pallas sweep: {ghs:.4f} GH/s -> {achieved_ops/1e12:.2f} T vector-ops/s")
    print(f"VPU theoretical peak: {VPU_PEAK_OPS/1e12:.2f} T u32-ops/s")
    print(f"roofline utilization: {achieved_ops/VPU_PEAK_OPS*100:.1f}%")
    print(f"op-bound ceiling at this census: {VPU_PEAK_OPS/spec_ops/1e9:.3f} GH/s")


if __name__ == "__main__":
    main()
