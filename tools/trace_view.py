"""Offline summarizer for -tracefile / dumptrace span dumps.

Usage:
    python tools/trace_view.py <trace.json>

Reads a Chrome-trace/perfetto JSON dump produced by util/telemetry
(``-tracefile`` at shutdown, or the ``dumptrace`` RPC mid-flight) and
prints:

  - a per-stage time table (count, total, mean, p50, p99 per span name);
  - the MEASURED pipeline overlap fraction, per block and aggregate: for
    every height with both a ``block.scan`` and a ``block.settle`` span,
    the in-flight window is scan-end -> settle-end (the signature batch
    is on the device for that whole stretch) and the blocked time is the
    settle span's duration — overlap = the fraction of the in-flight
    window the host spent doing useful work instead of waiting;
  - the top-10 slowest settles (the blocks worth profiling first);
  - a "reorg report" when the dump carries speculation-tree events
    (block.reorg / block.unwind / block.branch_drop instants, ISSUE 9):
    reorg depths, settle-failure unwinds, and losing-branch lifetimes;
  - a "signature serving" section when the dump carries SigService spans
    (serving.flush / serving.settle, ISSUE 7): flush-reason breakdown
    with lane counts, the flush->settle span-chain timing, and the list
    of deadline-miss instants (flushes that fired later than 2x the
    configured deadline).

Percentiles are nearest-rank over the raw span durations (exact, no
interpolation): sorted[ceil(q*n) - 1]. All times are milliseconds.

The report is plain deterministic text (golden-tested by
tests/unit/test_trace_view.py); pipe it wherever, or load the same JSON
at ui.perfetto.dev for the interactive view.
"""

from __future__ import annotations

import json
import math
import sys
from collections import defaultdict


def load(path: str) -> list[dict]:
    """Events from a dump: accepts both the wrapped {"traceEvents": []}
    object form and a bare event array."""
    with open(path) as f:
        obj = json.load(f)
    events = obj["traceEvents"] if isinstance(obj, dict) else obj
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome-trace dump")
    return events


def percentile(durs: list[float], q: float) -> float:
    """Nearest-rank percentile over raw values (exact)."""
    if not durs:
        return 0.0
    s = sorted(durs)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


def stage_table(events: list[dict]) -> list[tuple]:
    """[(name, count, total_ms, mean_ms, p50_ms, p99_ms)], total desc."""
    by_name: dict[str, list[float]] = defaultdict(list)
    for ev in events:
        if ev.get("ph") == "X":
            by_name[ev["name"]].append(float(ev.get("dur", 0.0)) / 1e3)
    rows = []
    for name, durs in by_name.items():
        total = sum(durs)
        rows.append((name, len(durs), total, total / len(durs),
                     percentile(durs, 0.5), percentile(durs, 0.99)))
    rows.sort(key=lambda r: (-r[2], r[0]))
    return rows


def block_overlap(events: list[dict]) -> list[dict]:
    """Per-block measured overlap: for each block with one block.scan
    and one block.settle span, in-flight = settle end - scan end and
    blocked = the settle span's duration. Returns
    [{height, scan_ms, settle_ms, inflight_ms, overlap}] height-ordered.

    Pairing keys on the span's ``hash`` arg when present (the pipelined
    engine stamps both spans with it) and falls back to height — pairing
    by height alone would marry an UNWOUND block's scan to the competing
    block's settle at the same height and overstate the in-flight
    window. Blocks missing either span (unwound blocks never settle) are
    skipped; a re-scan of the same block keeps the latest pair."""
    scans: dict[object, dict] = {}
    settles: dict[object, dict] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args", {})
        height = args.get("height")
        if height is None:
            continue
        key = args.get("hash", f"h{int(height)}")
        if ev["name"] == "block.scan":
            scans[key] = ev
        elif ev["name"] == "block.settle":
            settles[key] = ev
    out = []
    for key in sorted(
            set(scans) & set(settles),
            key=lambda k: int(scans[k]["args"]["height"])):
        scan, settle = scans[key], settles[key]
        height = int(scan["args"]["height"])
        scan_end = float(scan["ts"]) + float(scan.get("dur", 0.0))
        settle_end = float(settle["ts"]) + float(settle.get("dur", 0.0))
        inflight = (settle_end - scan_end) / 1e3
        blocked = float(settle.get("dur", 0.0)) / 1e3
        if inflight <= 0.0:
            continue
        out.append({
            "height": height,
            "scan_ms": float(scan.get("dur", 0.0)) / 1e3,
            "settle_ms": blocked,
            "inflight_ms": inflight,
            "overlap": max(0.0, min(1.0, 1.0 - blocked / inflight)),
        })
    return out


def serving_section(events: list[dict]) -> list[str]:
    """The SigService report lines (empty when the dump has no serving
    spans — keeps pre-serving dumps' reports byte-stable).

    The enqueue -> flush -> settle chain is read off the span structure:
    every serving.flush span is parented on its oldest lane's enqueue
    context and nests one serving.settle span, so flush duration minus
    settle duration is the host-side dispatch overhead."""
    flushes = [ev for ev in events
               if ev.get("ph") == "X" and ev.get("name") == "serving.flush"]
    settles = [ev for ev in events
               if ev.get("ph") == "X" and ev.get("name") == "serving.settle"]
    misses = [ev for ev in events
              if ev.get("ph") == "i"
              and ev.get("name") == "serving.deadline_miss"]
    if not (flushes or settles or misses):
        return []
    lines = ["", "signature serving (SigService)"]
    by_reason: dict[str, list[dict]] = defaultdict(list)
    for ev in flushes:
        by_reason[str(ev.get("args", {}).get("reason", "?"))].append(ev)
    lines.append(
        f"{'flush reason':<14}{'count':>7}{'lanes':>9}{'mean_ms':>10}"
        f"{'p99_ms':>10}")
    for reason in sorted(by_reason, key=lambda r: -len(by_reason[r])):
        evs = by_reason[reason]
        durs = [float(ev.get("dur", 0.0)) / 1e3 for ev in evs]
        lanes = sum(int(ev.get("args", {}).get("lanes", 0)) for ev in evs)
        lines.append(
            f"{reason:<14}{len(evs):>7}{lanes:>9}"
            f"{sum(durs) / len(durs):>10.2f}{percentile(durs, 0.99):>10.2f}")
    if flushes and settles:
        fd = [float(ev.get("dur", 0.0)) / 1e3 for ev in flushes]
        sd = [float(ev.get("dur", 0.0)) / 1e3 for ev in settles]
        lines += [
            "",
            "flush -> settle chain: "
            f"{len(flushes)} flush / {len(settles)} settle spans, "
            f"settle p50 {percentile(sd, 0.5):.2f} ms "
            f"p99 {percentile(sd, 0.99):.2f} ms, "
            f"dispatch overhead mean "
            f"{max(0.0, sum(fd) / len(fd) - sum(sd) / len(sd)):.2f} ms",
        ]
    if misses:
        lines += ["", f"deadline misses: {len(misses)}"]
        for ev in misses:
            a = ev.get("args", {})
            lines.append(
                f"  age {a.get('age_ms')} ms vs deadline "
                f"{a.get('deadline_ms')} ms ({a.get('lanes')} lane(s))")
    return lines


def reorg_section(events: list[dict]) -> list[str]:
    """The speculation-tree reorg report (empty when the dump carries no
    reorg/branch events — keeps pre-tree dumps' reports byte-stable).

    Reads three instant families the chainstate emits (ISSUE 9):
    ``block.reorg`` (settled blocks disconnected toward a new tip, with
    depth), ``block.unwind`` (a branch dropped by a settle FAILURE, with
    the failing block and how many speculative blocks went with it), and
    ``block.branch_drop`` (a losing branch dropped un-externalized when
    its competitor settled, with its lifetime)."""
    reorgs = [ev for ev in events
              if ev.get("ph") == "i" and ev.get("name") == "block.reorg"]
    unwinds = [ev for ev in events
               if ev.get("ph") == "i" and ev.get("name") == "block.unwind"]
    drops = [ev for ev in events
             if ev.get("ph") == "i"
             and ev.get("name") == "block.branch_drop"]
    if not (reorgs or drops):
        return []
    lines = ["", "reorg report (speculation tree)"]
    if reorgs:
        depths = [int(ev.get("args", {}).get("depth", 0)) for ev in reorgs]
        lines.append(
            f"reorgs: {len(reorgs)}  depth max {max(depths)} "
            f"mean {sum(depths) / len(depths):.2f}")
        for ev in reorgs:
            a = ev.get("args", {})
            lines.append(
                f"  depth {a.get('depth')} -> {a.get('to_hash')} "
                f"height {a.get('to_height')}")
    unwound = sum(int(ev.get("args", {}).get("dropped", 0))
                  for ev in unwinds)
    if unwinds:
        lines.append(
            f"settle-failure unwinds: {len(unwinds)} "
            f"({unwound} speculative block(s) dropped)")
    if drops:
        lives = [float(ev.get("args", {}).get("lifetime_ms", 0.0))
                 for ev in drops]
        blocks = sum(int(ev.get("args", {}).get("blocks", 0))
                     for ev in drops)
        lines.append(
            f"losing branches dropped: {len(drops)} ({blocks} block(s)), "
            f"lifetime mean {sum(lives) / len(lives):.1f} ms "
            f"max {max(lives):.1f} ms")
        for ev in drops:
            a = ev.get("args", {})
            lines.append(
                f"  branch {a.get('branch')} from height {a.get('height')}"
                f": {a.get('blocks')} block(s), {a.get('reason')}, "
                f"lived {float(a.get('lifetime_ms', 0.0)):.1f} ms")
    return lines


def summarize(events: list[dict]) -> str:
    """The full text report over one dump."""
    spans = [ev for ev in events if ev.get("ph") == "X"]
    lines = [
        f"trace summary: {len(events)} events, {len(spans)} spans",
        "",
        "per-stage time",
        f"{'stage':<28}{'count':>7}{'total_ms':>12}{'mean_ms':>10}"
        f"{'p50_ms':>10}{'p99_ms':>10}",
    ]
    for name, count, total, mean, p50, p99 in stage_table(events):
        lines.append(
            f"{name:<28}{count:>7}{total:>12.1f}{mean:>10.2f}"
            f"{p50:>10.2f}{p99:>10.2f}")

    blocks = block_overlap(events)
    lines += ["", "pipeline overlap (block.scan end -> block.settle end)"]
    if not blocks:
        lines.append("no block.scan/block.settle pairs in this trace")
    else:
        inflight = sum(b["inflight_ms"] for b in blocks)
        blocked = sum(b["settle_ms"] for b in blocks)
        agg = max(0.0, min(1.0, 1.0 - blocked / inflight)) if inflight \
            else 0.0
        lines.append(f"blocks measured: {len(blocks)}")
        lines.append(
            f"aggregate overlap fraction: {agg:.4f}  "
            f"(in-flight {inflight:.1f} ms, blocked {blocked:.1f} ms)")
        lines += ["", "top 10 slowest settles",
                  f"{'height':>8}{'settle_ms':>12}{'overlap':>10}"]
        slowest = sorted(blocks, key=lambda b: (-b["settle_ms"],
                                                b["height"]))[:10]
        for b in slowest:
            lines.append(f"{b['height']:>8}{b['settle_ms']:>12.2f}"
                         f"{b['overlap']:>10.4f}")

    lines += serving_section(events)
    lines += reorg_section(events)

    unwinds = [ev for ev in events
               if ev.get("ph") == "i" and ev.get("name") == "block.unwind"]
    if unwinds:
        lines += ["", f"unwinds: {len(unwinds)}"]
        for ev in unwinds:
            a = ev.get("args", {})
            lines.append(
                f"  height {a.get('height')}: dropped {a.get('dropped')} "
                f"block(s) ({a.get('reason')})")
    return "\n".join(lines) + "\n"


def main(argv: list[str]) -> int:
    if len(argv) != 2:
        print(__doc__.strip().splitlines()[0], file=sys.stderr)
        print(f"usage: {argv[0]} <trace.json>", file=sys.stderr)
        return 2
    sys.stdout.write(summarize(load(argv[1])))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
