#!/usr/bin/env python
"""linearize — export the active chain as a bootstrap.dat.

Reference: contrib/linearize/{linearize-hashes.py, linearize-data.py}
collapsed into one RPC-driven tool: walk getblockhash 0..tip (or --end),
fetch each raw block, and append height-ordered (netmagic, size, block)
records — the exact LoadExternalBlockFile framing, so the output feeds a
fresh node's -loadblock=<file> (or can be dropped into blocks/ and
-reindex'ed).

Usage:
  python tools/linearize.py --datadir /path/to/regtest-datadir \
      [--network regtest] [--rpcport N] [--start H] [--end H] \
      [--out bootstrap.dat]
"""

from __future__ import annotations

import argparse
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bitcoincashplus_tpu.consensus.params import select_params  # noqa: E402
from bitcoincashplus_tpu.rpc.client import RPCClient  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--datadir", required=True,
                    help="node datadir holding the RPC .cookie")
    ap.add_argument("--network", default="regtest",
                    choices=["main", "test", "regtest"])
    ap.add_argument("--rpcport", type=int, default=None)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--end", type=int, default=None,
                    help="last height to export (default: current tip)")
    ap.add_argument("--out", default="bootstrap.dat")
    args = ap.parse_args()

    params = select_params(args.network)
    port = args.rpcport or {"main": 8332, "test": 18332,
                            "regtest": 18443}[args.network]
    rpc = RPCClient(port=port, datadir=args.datadir)
    end = args.end if args.end is not None else rpc.getblockcount()

    n = 0
    with open(args.out, "wb") as f:
        for height in range(args.start, end + 1):
            raw = bytes.fromhex(rpc.getblock(rpc.getblockhash(height), 0))
            f.write(params.netmagic + struct.pack("<I", len(raw)) + raw)
            n += 1
    print(f"wrote {n} blocks (heights {args.start}..{end}) to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
