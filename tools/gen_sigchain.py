"""Synthetic signature-dense regtest chain generator — the workload for the
north-star reindex benchmark (BASELINE.json: "mainnet -reindex wall-clock").

Builds a regtest chain whose validation cost is dominated by ECDSA
signature checks (the same shape as a mainnet reindex above the checkpoint
era, src/init.cpp:~600 ThreadImport): a coinbase runway, fan-out
transactions splitting mature coinbases into thousands of P2PKH outputs,
then dense blocks of many-input P2PKH spends — every input one signature.

The chain is written through the normal BlockStore (blk?????.dat with
netmagic framing), so `bcpd -reindex` / Node(reindex) imports it through
exactly the code path the reference's LoadExternalBlockFile occupies.
Generation skips script verification (script_verifier=None) — blocks are
valid by construction (signed with the native signer, bit-identical to the
oracle) and the reindex run IS the validation.

CLI:  python tools/gen_sigchain.py --datadir D --sigs 40000
Emits one JSON line: {"blocks": N, "txs": N, "sigs": N, "bytes": N}.
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bitcoincashplus_tpu.consensus.block import CBlock, CBlockHeader  # noqa: E402
from bitcoincashplus_tpu.consensus.merkle import block_merkle_root  # noqa: E402
from bitcoincashplus_tpu.consensus.params import (  # noqa: E402
    get_block_subsidy,
    regtest_params,
)
from bitcoincashplus_tpu.consensus.pow import compact_to_target  # noqa: E402
from bitcoincashplus_tpu.consensus.tx import (  # noqa: E402
    COutPoint,
    CTransaction,
    CTxIn,
    CTxOut,
)
from bitcoincashplus_tpu.mining.assembler import (  # noqa: E402
    bip34_coinbase_script_sig,
)
from bitcoincashplus_tpu.store.blockstore import BlockStore  # noqa: E402
from bitcoincashplus_tpu.store.chainstatedb import CoinsDB  # noqa: E402
from bitcoincashplus_tpu.store.kvstore import KVStore  # noqa: E402
from bitcoincashplus_tpu.store.chainstatedb import BlockIndexDB  # noqa: E402
from bitcoincashplus_tpu.validation.chainstate import (  # noqa: E402
    ChainstateManager,
)
from bitcoincashplus_tpu.wallet.keys import CKey  # noqa: E402
from bitcoincashplus_tpu.wallet.signing import sign_transaction  # noqa: E402

FEE = 10_000  # flat per-tx fee (sat) — keeps every output above dust


def _mine(header: CBlockHeader, target: int) -> CBlockHeader:
    """Regtest difficulty-1 PoW: a couple of nonce tries on average."""
    from bitcoincashplus_tpu.crypto.hashes import sha256d

    nonce = 0
    raw = bytearray(header.serialize())
    while True:
        struct.pack_into("<I", raw, 76, nonce)
        if int.from_bytes(sha256d(bytes(raw)), "little") <= target:
            return header.with_nonce(nonce)
        nonce += 1


def _make_block(prev_hash: bytes, height: int, block_time: int, bits: int,
                target: int, txs: tuple, spk: bytes) -> CBlock:
    fees = FEE * (len(txs))
    coinbase = CTransaction(
        version=1,
        vin=(CTxIn(COutPoint(),
                   bip34_coinbase_script_sig(height) + b"sigchain", 0xFFFFFFFF),),
        vout=(CTxOut(fees + get_block_subsidy(height, regtest_params().consensus),
                     spk),),
    )
    vtx = (coinbase, *txs)

    class _V:
        pass

    v = _V()
    v.vtx = vtx
    root, _ = block_merkle_root(v)
    header = CBlockHeader(
        version=0x20000000, hash_prev_block=prev_hash, hash_merkle_root=root,
        time=block_time, bits=bits, nonce=0,
    )
    return CBlock(_mine(header, target), vtx)


def _mixed_phase(utxos, push, key, spk, total_sigs, inputs_per_tx,
                 progress):
    """Heterogeneous segment (VERDICT r4 #6): varied input counts across
    the dispatch padding buckets, P2PK spends (generic-interpreter deferred
    path), and P2SH 2-of-3 multisig spends (the eager CPU CHECKMULTISIG
    path) — the script-shape mix a real mainnet block range has, where the
    uniform P2PKH chain is the TPU fast path's best case. Returns the
    number of ECDSA checks generated."""
    import itertools

    from bitcoincashplus_tpu.crypto.hashes import hash160
    from bitcoincashplus_tpu.script.script import (
        multisig_script,
        p2pk_script,
        p2sh_script_for_redeem,
    )

    keys = [CKey(0xA11CE0 + 7 * i, compressed=(i % 2 == 0))
            for i in range(3)]
    redeem = multisig_script(2, [k.pubkey for k in keys])
    p2sh_spk = p2sh_script_for_redeem(redeem)
    pk_spk = p2pk_script(keys[0].pubkey)

    def key_for(ident):
        if ident in (key.pubkey_hash, key.pubkey):
            return key
        for k in keys:
            if ident in (k.pubkey_hash, k.pubkey):
                return k
        return None

    sizes = itertools.cycle([1, 3, 25, 80, min(250, inputs_per_tx)])
    sigs_done = 0
    carry = []  # (txid, idx, value, spk, redeem|None) to spend next block
    while (sigs_done < total_sigs and utxos) or carry:
        txs = []
        if carry:
            spent = [(s, v) for _, _, v, s, _ in carry]
            unsigned = CTransaction(
                version=1,
                vin=tuple(CTxIn(COutPoint(t, i), b"", 0xFFFFFFFE)
                          for t, i, _, _, _ in carry),
                vout=(CTxOut(sum(v for _, _, v, _, _ in carry) - FEE,
                             spk),),
            )
            rs = {hash160(r): r for *_, r in carry if r}
            txs.append(sign_transaction(unsigned, spent, key_for,
                                        enable_forkid=True,
                                        redeem_scripts=rs))
            sigs_done += sum(2 if r else 1 for *_, r in carry)
            carry = []
        if sigs_done < total_sigs and utxos:
            k = next(sizes)
            chunk = utxos[:k]
            del utxos[:k]
            total_in = sum(v for _, _, v in chunk)
            out_each = (total_in - FEE) // 3
            assert out_each > 546, "chunk too small for the 3-way split"
            unsigned = CTransaction(
                version=1,
                vin=tuple(CTxIn(COutPoint(t, i), b"", 0xFFFFFFFE)
                          for t, i, _ in chunk),
                vout=(CTxOut(out_each, pk_spk),
                      CTxOut(out_each, p2sh_spk),
                      CTxOut(out_each, spk)),
            )
            txs.append(sign_transaction(
                unsigned, [(spk, v) for _, _, v in chunk], key_for,
                enable_forkid=True))
            sigs_done += len(chunk)
            carry = [(txs[-1].txid, 0, out_each, pk_spk, None),
                     (txs[-1].txid, 1, out_each, p2sh_spk, redeem)]
        push(txs)
        progress(f"mixed block: {sigs_done}/{total_sigs} sigs")
    return sigs_done


def generate(datadir: str, total_sigs: int, inputs_per_tx: int = 250,
             txs_per_block: int = 8, fan_k: int = 2000,
             mixed: bool = False,
             progress=lambda s: None) -> dict:
    params = regtest_params()
    net_dir = os.path.join(datadir, "regtest")
    blocks_dir = os.path.join(net_dir, "blocks")
    os.makedirs(blocks_dir, exist_ok=True)

    index_kv = KVStore(os.path.join(blocks_dir, "index.sqlite"))
    coins_kv = KVStore(os.path.join(net_dir, "chainstate.sqlite"))
    store = BlockStore(net_dir, params.netmagic)
    cs = ChainstateManager(
        params, CoinsDB(coins_kv), store, script_verifier=None,
        index_db=BlockIndexDB(index_kv),
    )

    key = CKey(0x53C5A1F4E0B1DE5FCE, compressed=True)
    spk = key.p2pkh_script()

    def key_for_id(ident):
        return key if ident in (key.pubkey_hash, key.pubkey) else None

    bits = params.genesis.header.bits
    target, _ = compact_to_target(bits)
    t = [params.genesis.header.time]
    n_blocks = [0]
    n_txs = [0]
    n_bytes = [0]

    def push(txs=()):
        tip = cs.tip()
        t[0] += 60
        blk = _make_block(tip.hash, tip.height + 1, t[0], bits, target,
                          tuple(txs), spk)
        cs.process_new_block(blk)
        n_blocks[0] += 1
        n_txs[0] += len(blk.vtx)
        n_bytes[0] += len(blk.serialize())
        return blk

    # Phase 1: coinbase runway. Fan-out txs each consume one MATURE (100+
    # deep) coinbase, so mint enough and add the maturity padding.
    sigs_per_dense_block = inputs_per_tx * txs_per_block
    n_fan = (total_sigs + fan_k - 1) // fan_k
    runway = n_fan + 100
    progress(f"runway: {runway} coinbase blocks")
    coinbases = []  # (txid, vout_value, height)
    for _ in range(runway):
        blk = push()
        coinbases.append((blk.vtx[0].txid, blk.vtx[0].vout[0].value))
    coinbases = coinbases[:n_fan]

    # Phase 2: fan-out — split each mature coinbase into fan_k P2PKH outputs.
    progress(f"fan-out: {n_fan} txs x {fan_k} outputs")
    utxos = []  # (txid, index, value)
    fan_batch = []
    for txid, value in coinbases:
        per_out = (value - FEE) // fan_k
        assert per_out > 546, "fan_k too large for the subsidy"
        unsigned = CTransaction(
            version=1,
            vin=(CTxIn(COutPoint(txid, 0), b"", 0xFFFFFFFE),),
            vout=tuple(CTxOut(per_out, spk) for _ in range(fan_k)),
        )
        signed = sign_transaction(unsigned, [(spk, value)], key_for_id,
                                  enable_forkid=True)
        fan_batch.append(signed)
        for i in range(fan_k):
            utxos.append((signed.txid, i, per_out))
        if len(fan_batch) == 5:
            push(fan_batch)
            fan_batch = []
    if fan_batch:
        push(fan_batch)

    # Phase 3: dense blocks — txs_per_block txs of inputs_per_tx P2PKH
    # spends each; every input is one ECDSA verification at reindex.
    # (mixed=True swaps in the heterogeneous segment instead.)
    utxos = utxos[:total_sigs]
    if mixed:
        n_sigs = _mixed_phase(utxos, push, key, spk, total_sigs,
                              inputs_per_tx, progress)
        store.flush()
        cs.flush()
        store.close()
        index_kv.close()
        coins_kv.close()
        return {
            "blocks": n_blocks[0],
            "txs": n_txs[0],
            "sigs": n_sigs,
            "bytes": n_bytes[0],
            "tip_height": n_blocks[0],
            "mixed": True,
        }
    progress(f"dense: {len(utxos)} sig-inputs, "
             f"{sigs_per_dense_block} per block")
    sigs_done = 0
    pos = 0
    t0 = time.monotonic()
    while pos < len(utxos):
        txs = []
        for _ in range(txs_per_block):
            chunk = utxos[pos:pos + inputs_per_tx]
            if not chunk:
                break
            pos += len(chunk)
            total_in = sum(v for _, _, v in chunk)
            unsigned = CTransaction(
                version=1,
                vin=tuple(CTxIn(COutPoint(txid, i), b"", 0xFFFFFFFE)
                          for txid, i, _ in chunk),
                vout=(CTxOut(total_in - FEE, spk),),
            )
            txs.append(sign_transaction(
                unsigned, [(spk, v) for _, _, v in chunk], key_for_id,
                enable_forkid=True,
            ))
        blk = push(txs)
        sigs_done = pos
        progress(f"dense block {n_blocks[0]}: {sigs_done}/{len(utxos)} sigs "
                 f"({sigs_done / (time.monotonic() - t0):.0f} sigs/s gen)")

    store.flush()
    cs.flush()
    store.close()
    index_kv.close()
    coins_kv.close()
    return {
        "blocks": n_blocks[0],
        "txs": n_txs[0],
        "sigs": len(utxos),
        "bytes": n_bytes[0],
        "tip_height": n_blocks[0],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--datadir", required=True)
    ap.add_argument("--sigs", type=int, default=40_000)
    ap.add_argument("--inputs-per-tx", type=int, default=250)
    ap.add_argument("--txs-per-block", type=int, default=8)
    ap.add_argument("--fan-k", type=int, default=2000)
    ap.add_argument("--mixed", action="store_true",
                    help="heterogeneous script shapes (see _mixed_phase)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args()
    progress = (lambda s: None) if args.quiet else (
        lambda s: print(f"[gen_sigchain] {s}", file=sys.stderr, flush=True))
    summary = generate(args.datadir, args.sigs, args.inputs_per_tx,
                       args.txs_per_block, args.fan_k, args.mixed, progress)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
