"""Generate the committed consensus vector corpus (tests/data/*.json).

SURVEY.md §5.4(2)/§8.6(d): the reference pins consensus behavior with
data-driven vector files (src/test/data/script_tests.json, sighash.json,
tx_valid.json, tx_invalid.json). This generator re-derives an equivalent
corpus from THIS framework's trusted signer + interpreter (both themselves
differential-tested against library oracles), asserting every authored
expectation against the interpreter as it emits — so a mismatch aborts
generation rather than committing a wrong vector. The committed JSON then
locks current consensus behavior against regressions.

Usage:  python tools/gen_vectors.py          # writes tests/data/*.json
Runner: tests/unit/test_script_vectors.py    # replays in the default suite

Formats (self-describing; first element of each file is a comment string):
  script_tests.json entries: [scriptSig_hex, scriptPubKey_hex, flags, expect, desc]
  sighash.json      entries: [tx_hex, scriptCode_hex, in_idx, hashtype, amount,
                              legacy_digest_hex_or_None, forkid_digest_hex]
  tx_valid/invalid  entries: {inputs: [[prevtxid_hex, n, spk_hex, amount]...],
                              tx: hex, flags: str, expect: str, desc: str}
"""

from __future__ import annotations

import json
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from bitcoincashplus_tpu.consensus.serialize import ByteReader
from bitcoincashplus_tpu.consensus.tx import (
    COutPoint,
    CTransaction,
    CTxIn,
    CTxOut,
)
from bitcoincashplus_tpu.crypto.hashes import hash160, ripemd160, sha256, sha256d
from bitcoincashplus_tpu.script import script as S
from bitcoincashplus_tpu.script.interpreter import (
    SCRIPT_ENABLE_SIGHASH_FORKID,
    SCRIPT_VERIFY_CHECKLOCKTIMEVERIFY,
    SCRIPT_VERIFY_CHECKSEQUENCEVERIFY,
    SCRIPT_VERIFY_CLEANSTACK,
    SCRIPT_VERIFY_DERSIG,
    SCRIPT_VERIFY_DISCOURAGE_UPGRADABLE_NOPS,
    SCRIPT_VERIFY_LOW_S,
    SCRIPT_VERIFY_MINIMALDATA,
    SCRIPT_VERIFY_NONE,
    SCRIPT_VERIFY_NULLDUMMY,
    SCRIPT_VERIFY_NULLFAIL,
    SCRIPT_VERIFY_P2SH,
    SCRIPT_VERIFY_SIGPUSHONLY,
    SCRIPT_VERIFY_STRICTENC,
    ScriptError,
    TransactionSignatureChecker,
    VerifyScript,
)
from bitcoincashplus_tpu.script.sighash import (
    SIGHASH_ALL,
    SIGHASH_ANYONECANPAY,
    SIGHASH_FORKID,
    SIGHASH_NONE,
    SIGHASH_SINGLE,
    signature_hash_forkid,
    signature_hash_legacy,
)
from bitcoincashplus_tpu.crypto import secp256k1 as secp
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import make_signature, sign_transaction

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "tests", "data")

FLAG_BITS = {
    "P2SH": SCRIPT_VERIFY_P2SH,
    "STRICTENC": SCRIPT_VERIFY_STRICTENC,
    "DERSIG": SCRIPT_VERIFY_DERSIG,
    "LOW_S": SCRIPT_VERIFY_LOW_S,
    "NULLDUMMY": SCRIPT_VERIFY_NULLDUMMY,
    "SIGPUSHONLY": SCRIPT_VERIFY_SIGPUSHONLY,
    "MINIMALDATA": SCRIPT_VERIFY_MINIMALDATA,
    "DISCOURAGE_UPGRADABLE_NOPS": SCRIPT_VERIFY_DISCOURAGE_UPGRADABLE_NOPS,
    "CLEANSTACK": SCRIPT_VERIFY_CLEANSTACK,
    "CHECKLOCKTIMEVERIFY": SCRIPT_VERIFY_CHECKLOCKTIMEVERIFY,
    "CHECKSEQUENCEVERIFY": SCRIPT_VERIFY_CHECKSEQUENCEVERIFY,
    "NULLFAIL": SCRIPT_VERIFY_NULLFAIL,
    "FORKID": SCRIPT_ENABLE_SIGHASH_FORKID,
}


def parse_flags(s: str) -> int:
    f = SCRIPT_VERIFY_NONE
    if s:
        for name in s.split(","):
            f |= FLAG_BITS[name]
    return f


KEY = CKey(0x1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1CE1)
KEY2 = CKey(0x2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B2B)
KEY3 = CKey(0x3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C3C)
AMOUNT = 12_3456_7890  # satoshis credited in every script-test context


def build_ctx(script_sig: bytes, script_pubkey: bytes,
              amount: int = AMOUNT, sequence: int = 0xFFFFFFFF,
              locktime: int = 0):
    """Crediting + spending transaction pair — the fixed context every
    script_tests vector runs in (mirrors the reference's
    BuildCreditingTransaction/BuildSpendingTransaction convention)."""
    credit = CTransaction(
        version=1,
        vin=(CTxIn(COutPoint(), b"\x00\x00"),),
        vout=(CTxOut(amount, script_pubkey),),
        locktime=0,
    )
    spend = CTransaction(
        version=1,
        vin=(CTxIn(COutPoint(credit.txid, 0), script_sig, sequence),),
        vout=(CTxOut(amount, b""),),
        locktime=locktime,
    )
    return credit, spend


def run_script_vector(sig_hex: str, spk_hex: str, flags_str: str) -> str:
    sig, spk = bytes.fromhex(sig_hex), bytes.fromhex(spk_hex)
    _, spend = build_ctx(sig, spk)
    checker = TransactionSignatureChecker(spend, 0, AMOUNT)
    try:
        VerifyScript(sig, spk, parse_flags(flags_str), checker)
        return "OK"
    except ScriptError as e:
        return e.code


SCRIPT_VECTORS: list[list[str]] = []


def vec(sig: bytes, spk: bytes, flags: str, expect: str, desc: str):
    got = run_script_vector(sig.hex(), spk.hex(), flags)
    if got != expect:
        raise SystemExit(
            f"VECTOR MISMATCH: {desc!r}\n  sig={sig.hex()} spk={spk.hex()} "
            f"flags={flags}\n  expected {expect}, interpreter says {got}"
        )
    SCRIPT_VECTORS.append([sig.hex(), spk.hex(), flags, expect, desc])


def op(*codes) -> bytes:
    return bytes(codes)


def push(data: bytes) -> bytes:
    return S.push_data_raw(data)


def pushnum(n: int) -> bytes:
    """Minimal push of small number n."""
    if n == 0:
        return b"\x00"
    if 1 <= n <= 16:
        return bytes([0x50 + n])
    if n == -1:
        return bytes([S.OP_1NEGATE])
    return push(S.script_num_encode(n) if hasattr(S, "script_num_encode")
                else _num(n))


def _num(n: int) -> bytes:
    """Script-number encode (minimal)."""
    if n == 0:
        return b""
    neg = n < 0
    n = abs(n)
    out = bytearray()
    while n:
        out.append(n & 0xFF)
        n >>= 8
    if out[-1] & 0x80:
        out.append(0x80 if neg else 0x00)
    elif neg:
        out[-1] |= 0x80
    return bytes(out)


def make_ctx_signature(script_code: bytes, hashtype: int, *, key=KEY,
                       forkid=False, amount=AMOUNT) -> bytes:
    """Signature over the standard script-test context for `script_code`."""
    _, spend = build_ctx(b"", script_code, amount)
    return make_signature(key, script_code, spend, 0, amount, hashtype,
                          enable_forkid=forkid)


def gen_script_vectors():
    OPC = S  # opcode namespace

    # ---- trivial / truthiness ----
    vec(b"", op(OPC.OP_1), "", "OK", "empty sig, OP_1")
    vec(b"", b"", "", "eval-false", "both empty: empty final stack")
    vec(b"\x00", b"", "", "eval-false", "push empty -> false")
    vec(pushnum(1), b"", "", "OK", "OP_1 alone is true")
    vec(b"", op(OPC.OP_0), "", "eval-false", "OP_0 -> false")

    # ---- pushes: OP_1..OP_16 round-trip through EQUAL ----
    for n in range(1, 17):
        vec(bytes([0x50 + n]), push(bytes([n])) + op(OPC.OP_EQUAL), "",
            "OK", f"OP_{n} equals direct push")
    vec(bytes([OPC.OP_1NEGATE]), push(b"\x81") + op(OPC.OP_EQUAL), "",
        "OK", "OP_1NEGATE encoding")

    # ---- PUSHDATA forms vs MINIMALDATA ----
    data = b"\x42"
    forms = {
        "direct": push(data),
        "pushdata1": bytes([OPC.OP_PUSHDATA1, 1]) + data,
        "pushdata2": bytes([OPC.OP_PUSHDATA2, 1, 0]) + data,
        "pushdata4": bytes([OPC.OP_PUSHDATA4, 1, 0, 0, 0]) + data,
    }
    for name, frm in forms.items():
        vec(frm, push(data) + op(OPC.OP_EQUAL), "", "OK",
            f"{name} push accepted without MINIMALDATA")
        expect = "OK" if name == "direct" else "minimaldata"
        vec(frm, push(data) + op(OPC.OP_EQUAL), "MINIMALDATA", expect,
            f"{name} push under MINIMALDATA")
    # number-encoded-as-data must use OP_n form under MINIMALDATA
    vec(push(b"\x01"), op(OPC.OP_1, OPC.OP_EQUAL), "MINIMALDATA",
        "minimaldata", "0x01 data push where OP_1 required")

    # ---- push size limits ----
    vec(push(b"\x6a" * 520), op(OPC.OP_SIZE) + push(_num(520)) +
        op(OPC.OP_EQUALVERIFY, OPC.OP_SIZE, OPC.OP_0NOTEQUAL), "",
        "OK", "520-byte push is legal (MAX_SCRIPT_ELEMENT_SIZE)")
    big = b"\x6a" * 521
    vec(bytes([OPC.OP_PUSHDATA2]) + len(big).to_bytes(2, "little") + big,
        op(OPC.OP_DROP, OPC.OP_1), "", "push-size", "521-byte push rejected")

    # ---- control flow ----
    vec(pushnum(1), op(OPC.OP_IF, OPC.OP_1, OPC.OP_ELSE, OPC.OP_0,
                       OPC.OP_ENDIF), "", "OK", "IF true branch")
    vec(pushnum(0), op(OPC.OP_IF, OPC.OP_0, OPC.OP_ELSE, OPC.OP_1,
                       OPC.OP_ENDIF), "", "OK", "ELSE branch")
    vec(pushnum(0), op(OPC.OP_NOTIF, OPC.OP_1, OPC.OP_ENDIF), "",
        "OK", "NOTIF on false")
    vec(pushnum(1), op(OPC.OP_IF, OPC.OP_1), "", "unbalanced-conditional",
        "IF without ENDIF")
    vec(b"", op(OPC.OP_ELSE, OPC.OP_1), "", "unbalanced-conditional",
        "ELSE without IF")
    vec(b"", op(OPC.OP_ENDIF, OPC.OP_1), "", "unbalanced-conditional",
        "ENDIF without IF")
    vec(b"", op(OPC.OP_IF, OPC.OP_1, OPC.OP_ENDIF), "",
        "invalid-stack-operation", "IF with empty stack")
    vec(pushnum(0) + pushnum(1),
        op(OPC.OP_IF, OPC.OP_IF, OPC.OP_0, OPC.OP_ELSE, OPC.OP_1,
           OPC.OP_ENDIF, OPC.OP_ELSE, OPC.OP_0, OPC.OP_ENDIF),
        "", "OK", "nested IF: outer true, inner false takes inner ELSE")

    # ---- VERIFY / RETURN ----
    vec(pushnum(1), op(OPC.OP_VERIFY, OPC.OP_1), "", "OK", "VERIFY true")
    vec(pushnum(0), op(OPC.OP_VERIFY, OPC.OP_1), "", "verify", "VERIFY false")
    vec(b"", op(OPC.OP_RETURN), "", "op-return", "OP_RETURN fails")
    vec(pushnum(1), op(OPC.OP_RETURN), "", "op-return",
        "OP_RETURN fails with true on stack")

    # ---- stack ops ----
    vec(pushnum(7), op(OPC.OP_DUP, OPC.OP_EQUAL), "", "OK", "DUP")
    vec(pushnum(1) + pushnum(0), op(OPC.OP_DROP), "", "OK", "DROP")
    vec(pushnum(1) + pushnum(2),
        op(OPC.OP_SWAP) + pushnum(1) + op(OPC.OP_EQUALVERIFY) + pushnum(2) +
        op(OPC.OP_EQUAL), "", "OK", "SWAP order")
    vec(b"", op(OPC.OP_DUP), "", "invalid-stack-operation",
        "DUP on empty stack")
    vec(pushnum(5), op(OPC.OP_DEPTH, OPC.OP_1, OPC.OP_EQUALVERIFY,
                       OPC.OP_5, OPC.OP_EQUAL), "", "OK", "DEPTH counts")
    vec(pushnum(1) + pushnum(2) + pushnum(3),
        op(OPC.OP_ROT) + pushnum(1) + op(OPC.OP_EQUALVERIFY) + pushnum(3) +
        op(OPC.OP_EQUALVERIFY) + pushnum(2) + op(OPC.OP_EQUAL),
        "", "OK", "ROT rotation")
    vec(pushnum(9) + pushnum(8),
        op(OPC.OP_OVER) + pushnum(9) + op(OPC.OP_EQUALVERIFY, OPC.OP_2DROP,
                                          OPC.OP_1), "", "OK", "OVER copies")
    vec(pushnum(4) + pushnum(5) + pushnum(1),
        op(OPC.OP_PICK) + pushnum(4) + op(OPC.OP_EQUALVERIFY, OPC.OP_2DROP,
                                          OPC.OP_1), "", "OK", "PICK depth 1")
    vec(pushnum(4) + pushnum(5) + pushnum(1),
        op(OPC.OP_ROLL) + pushnum(4) + op(OPC.OP_EQUALVERIFY, OPC.OP_DROP,
                                          OPC.OP_1), "", "OK", "ROLL depth 1")
    vec(pushnum(3), op(OPC.OP_IFDUP, OPC.OP_EQUAL), "", "OK",
        "IFDUP duplicates nonzero")
    vec(pushnum(6), op(OPC.OP_TOALTSTACK, OPC.OP_FROMALTSTACK) + pushnum(6) +
        op(OPC.OP_EQUAL), "", "OK", "altstack round trip")
    vec(b"", op(OPC.OP_FROMALTSTACK), "", "invalid-altstack-operation",
        "FROMALTSTACK empty")
    vec(pushnum(1) + pushnum(2), op(OPC.OP_NIP) + pushnum(2) +
        op(OPC.OP_EQUAL), "", "OK", "NIP removes second")
    vec(pushnum(1) + pushnum(2),
        op(OPC.OP_TUCK, OPC.OP_DEPTH, OPC.OP_3, OPC.OP_EQUALVERIFY,
           OPC.OP_2DROP), "", "OK", "TUCK inserts copy")

    # ---- numeric ----
    vec(pushnum(2) + pushnum(3), op(OPC.OP_ADD, OPC.OP_5, OPC.OP_EQUAL),
        "", "OK", "2+3=5")
    vec(pushnum(5) + pushnum(3), op(OPC.OP_SUB, OPC.OP_2, OPC.OP_EQUAL),
        "", "OK", "5-3=2")
    vec(pushnum(5), op(OPC.OP_NEGATE) + push(b"\x85") + op(OPC.OP_EQUAL),
        "", "OK", "NEGATE encoding")
    vec(push(b"\x85"), op(OPC.OP_ABS, OPC.OP_5, OPC.OP_EQUAL), "", "OK",
        "ABS(-5)")
    vec(pushnum(0), op(OPC.OP_NOT), "", "OK", "NOT 0 = 1")
    vec(pushnum(11), op(OPC.OP_0NOTEQUAL), "", "OK", "0NOTEQUAL")
    vec(pushnum(2) + pushnum(7), op(OPC.OP_MAX, OPC.OP_7, OPC.OP_EQUAL),
        "", "OK", "MAX")
    vec(pushnum(2) + pushnum(7), op(OPC.OP_MIN, OPC.OP_2, OPC.OP_EQUAL),
        "", "OK", "MIN")
    vec(pushnum(5) + pushnum(1) + pushnum(10), op(OPC.OP_WITHIN), "", "OK",
        "WITHIN [1,10)")
    vec(pushnum(1) + pushnum(1), op(OPC.OP_BOOLAND), "", "OK", "BOOLAND")
    vec(pushnum(0) + pushnum(1), op(OPC.OP_BOOLOR), "", "OK", "BOOLOR")
    vec(pushnum(3) + pushnum(3), op(OPC.OP_NUMEQUAL), "", "OK", "NUMEQUAL")
    vec(pushnum(2) + pushnum(3), op(OPC.OP_LESSTHAN), "", "OK", "LESSTHAN")
    vec(pushnum(3) + pushnum(2), op(OPC.OP_GREATERTHAN), "", "OK",
        "GREATERTHAN")
    vec(pushnum(1), op(OPC.OP_1ADD, OPC.OP_2, OPC.OP_EQUAL), "", "OK", "1ADD")
    vec(pushnum(2), op(OPC.OP_1SUB, OPC.OP_1, OPC.OP_EQUAL), "", "OK", "1SUB")
    # 5-byte number operand overflows CScriptNum
    vec(push(b"\xff\xff\xff\xff\x7f"), op(OPC.OP_1ADD, OPC.OP_DROP, OPC.OP_1),
        "", "unknown-error", "5-byte scriptnum operand rejected")
    # but 5-byte result of arithmetic is fine to produce and compare raw
    vec(push(b"\xff\xff\xff\x7f") + op(OPC.OP_DUP, OPC.OP_ADD),
        push(b"\xfe\xff\xff\xff\x00") + op(OPC.OP_EQUAL), "",
        "OK", "4-byte operands may produce 5-byte result")

    # ---- hashing opcodes ----
    msg = b"tpu"
    vec(push(msg), op(OPC.OP_SHA256) + push(sha256(msg)) + op(OPC.OP_EQUAL),
        "", "OK", "SHA256 vector")
    vec(push(msg), op(OPC.OP_HASH256) + push(sha256d(msg)) + op(OPC.OP_EQUAL),
        "", "OK", "HASH256 vector")
    vec(push(msg), op(OPC.OP_RIPEMD160) + push(ripemd160(msg)) +
        op(OPC.OP_EQUAL), "", "OK", "RIPEMD160 vector")
    vec(push(msg), op(OPC.OP_HASH160) + push(hash160(msg)) + op(OPC.OP_EQUAL),
        "", "OK", "HASH160 vector")

    # ---- disabled opcodes: fail even in unexecuted branches ----
    for name in ("OP_CAT", "OP_SUBSTR", "OP_LEFT", "OP_RIGHT", "OP_INVERT",
                 "OP_AND", "OP_OR", "OP_XOR", "OP_2MUL", "OP_2DIV", "OP_MUL",
                 "OP_DIV", "OP_MOD", "OP_LSHIFT", "OP_RSHIFT"):
        code = getattr(OPC, name)
        vec(pushnum(0), op(OPC.OP_IF, code, OPC.OP_ENDIF, OPC.OP_1), "",
            "disabled-opcode", f"{name} disabled even unexecuted")

    # ---- NOPs and upgradable NOPs ----
    vec(b"", op(OPC.OP_NOP, OPC.OP_1), "", "OK", "NOP")
    for nop in (OPC.OP_NOP1, OPC.OP_NOP4, OPC.OP_NOP10):
        vec(b"", op(nop, OPC.OP_1), "", "OK", "upgradable NOP without flag")
        vec(b"", op(nop, OPC.OP_1), "DISCOURAGE_UPGRADABLE_NOPS",
            "discourage-upgradable-nops", "upgradable NOP discouraged")

    # ---- CLTV / CSV (context-free failure modes; success in tx_valid) ----
    vec(b"", op(OPC.OP_CHECKLOCKTIMEVERIFY, OPC.OP_1), "CHECKLOCKTIMEVERIFY",
        "invalid-stack-operation", "CLTV empty stack")
    vec(push(b"\x81"), op(OPC.OP_CHECKLOCKTIMEVERIFY, OPC.OP_DROP, OPC.OP_1),
        "CHECKLOCKTIMEVERIFY", "negative-locktime", "CLTV negative")
    vec(pushnum(1), op(OPC.OP_CHECKLOCKTIMEVERIFY, OPC.OP_DROP, OPC.OP_1),
        "CHECKLOCKTIMEVERIFY", "unsatisfied-locktime",
        "CLTV unmet (tx locktime 0)")
    vec(b"", op(OPC.OP_CHECKSEQUENCEVERIFY, OPC.OP_1), "CHECKSEQUENCEVERIFY",
        "invalid-stack-operation", "CSV empty stack")
    vec(push(b"\x81"), op(OPC.OP_CHECKSEQUENCEVERIFY, OPC.OP_DROP, OPC.OP_1),
        "CHECKSEQUENCEVERIFY", "negative-locktime", "CSV negative")
    vec(pushnum(1), op(OPC.OP_CHECKLOCKTIMEVERIFY, OPC.OP_DROP, OPC.OP_1),
        "", "OK", "CLTV is a NOP without its flag")

    # ---- P2SH ----
    redeem = op(OPC.OP_1)
    p2sh = S.p2sh_script_for_redeem(redeem)
    vec(push(redeem), p2sh, "P2SH", "OK", "P2SH redeem OP_1")
    vec(push(redeem), p2sh, "", "OK", "P2SH pattern is plain hash-EQUAL pre-flag")
    vec(push(op(OPC.OP_0)), p2sh, "P2SH", "eval-false",
        "P2SH wrong redeem hash")
    vec(op(OPC.OP_NOP) + push(redeem), p2sh, "P2SH", "sig-pushonly",
        "P2SH scriptSig must be push-only")
    redeem_false = op(OPC.OP_0)
    p2sh_false = S.p2sh_script_for_redeem(redeem_false)
    vec(push(redeem_false), p2sh_false, "P2SH", "eval-false",
        "P2SH redeem evaluates false")
    vec(pushnum(1) + push(redeem), p2sh, "P2SH,CLEANSTACK", "cleanstack",
        "extra stack element under CLEANSTACK")
    vec(push(redeem), p2sh, "P2SH,CLEANSTACK", "OK", "CLEANSTACK clean")
    vec(op(OPC.OP_NOP) + pushnum(1), op(OPC.OP_1), "SIGPUSHONLY",
        "sig-pushonly", "SIGPUSHONLY rejects non-push scriptSig")

    # ---- CHECKSIG family ----
    spk_pk = push(KEY.pubkey) + op(OPC.OP_CHECKSIG)
    sig_ok = make_ctx_signature(spk_pk, SIGHASH_ALL)
    vec(push(sig_ok), spk_pk, "", "OK", "P2PK valid sig (legacy ALL)")
    vec(push(sig_ok), spk_pk, "STRICTENC,DERSIG,LOW_S,NULLFAIL", "OK",
        "P2PK valid sig passes strict bundle")
    # forkid signature
    sig_fid = make_ctx_signature(spk_pk, SIGHASH_ALL | SIGHASH_FORKID,
                                 forkid=True)
    vec(push(sig_fid), spk_pk, "FORKID,STRICTENC", "OK",
        "P2PK valid FORKID sig")
    vec(push(sig_fid), spk_pk, "STRICTENC", "illegal-forkid",
        "FORKID bit without FORKID flag")
    vec(push(sig_ok), spk_pk, "FORKID,STRICTENC", "must-use-forkid",
        "legacy sig when FORKID active")
    # tampered sig
    bad = bytearray(sig_ok)
    bad[10] ^= 0x01
    vec(push(bytes(bad)), spk_pk, "", "eval-false",
        "tampered sig -> false, no NULLFAIL")
    vec(push(bytes(bad)), spk_pk, "NULLFAIL", "sig-nullfail",
        "tampered sig under NULLFAIL")
    vec(b"\x00", spk_pk, "NULLFAIL", "eval-false",
        "empty sig may fail quietly under NULLFAIL")
    # P2PKH
    spk_pkh = KEY.p2pkh_script()
    sig_pkh = make_ctx_signature(spk_pkh, SIGHASH_ALL)
    vec(push(sig_pkh) + push(KEY.pubkey), spk_pkh, "", "OK",
        "P2PKH valid spend")
    vec(push(sig_pkh) + push(KEY2.pubkey), spk_pkh, "", "equalverify",
        "P2PKH wrong pubkey")
    # CHECKSIGVERIFY
    spk_csv = push(KEY.pubkey) + op(OPC.OP_CHECKSIGVERIFY, OPC.OP_1)
    sig_csv = make_ctx_signature(spk_csv, SIGHASH_ALL)
    vec(push(sig_csv), spk_csv, "", "OK", "CHECKSIGVERIFY valid")
    vec(b"\x00", spk_csv, "", "checksigverify", "CHECKSIGVERIFY empty sig")
    # hashtype variants (legacy + forkid)
    for ht, name in ((SIGHASH_NONE, "NONE"), (SIGHASH_SINGLE, "SINGLE"),
                     (SIGHASH_ALL | SIGHASH_ANYONECANPAY, "ALL|ACP"),
                     (SIGHASH_NONE | SIGHASH_ANYONECANPAY, "NONE|ACP"),
                     (SIGHASH_SINGLE | SIGHASH_ANYONECANPAY, "SINGLE|ACP")):
        s = make_ctx_signature(spk_pk, ht)
        vec(push(s), spk_pk, "STRICTENC", "OK", f"legacy {name} sig")
        s = make_ctx_signature(spk_pk, ht | SIGHASH_FORKID, forkid=True)
        vec(push(s), spk_pk, "FORKID,STRICTENC", "OK", f"forkid {name} sig")
    # bad hashtype byte under STRICTENC
    s20 = sig_ok[:-1] + b"\x20"
    vec(push(s20), spk_pk, "STRICTENC", "sig-hashtype",
        "undefined hashtype under STRICTENC")
    vec(push(s20), spk_pk, "", "eval-false",
        "undefined hashtype merely fails without STRICTENC")
    # high-S
    r, s_val = secp.sig_der_decode(sig_ok)
    hi = secp.N - s_val
    if hi < s_val:
        r, s_val, hi = r, hi, s_val  # ensure hi is the high one
        sig_low_body = secp.sig_der_encode(r, s_val)
    high_sig = secp.sig_der_encode(r, max(s_val, secp.N - s_val)) + b"\x01"
    low_sig = secp.sig_der_encode(r, min(s_val, secp.N - s_val)) + b"\x01"
    # exactly one of the two verifies as the original; find which
    vec(push(high_sig), spk_pk, "LOW_S", "sig-high-s",
        "high-S rejected under LOW_S")
    # non-canonical DER (long-form length) — lax parse ok, DERSIG rejects
    body = sig_ok[:-1]
    assert body[0] == 0x30
    lax = b"\x30\x81" + bytes([body[1]]) + body[2:] + b"\x01"
    vec(push(lax), spk_pk, "", "OK",
        "BER long-form length accepted pre-DERSIG (parse_der_lax)")
    vec(push(lax), spk_pk, "DERSIG", "sig-der",
        "BER long-form length rejected by DERSIG")
    # hybrid pubkey encoding under STRICTENC
    uncompressed = secp.privkey_to_pubkey(KEY.secret, compressed=False)
    hybrid = b"\x06" + uncompressed[1:]
    spk_hyb = push(hybrid) + op(OPC.OP_CHECKSIG)
    vec(b"\x00", spk_hyb, "STRICTENC", "pubkeytype",
        "hybrid pubkey under STRICTENC")
    vec(b"\x00", spk_hyb, "", "eval-false",
        "hybrid pubkey merely fails without STRICTENC")

    # ---- CHECKMULTISIG ----
    keys2 = [KEY, KEY2]
    ms12 = S.multisig_script(1, [k.pubkey for k in keys2])
    s1 = make_ctx_signature(ms12, SIGHASH_ALL)
    vec(b"\x00" + push(s1), ms12, "", "OK", "1-of-2 multisig (key 1)")
    s2 = make_ctx_signature(ms12, SIGHASH_ALL, key=KEY2)
    vec(b"\x00" + push(s2), ms12, "", "OK", "1-of-2 multisig (key 2)")
    ms23 = S.multisig_script(2, [k.pubkey for k in (KEY, KEY2, KEY3)])
    sa = make_ctx_signature(ms23, SIGHASH_ALL)
    sb = make_ctx_signature(ms23, SIGHASH_ALL, key=KEY2)
    sc = make_ctx_signature(ms23, SIGHASH_ALL, key=KEY3)
    vec(b"\x00" + push(sa) + push(sb), ms23, "", "OK", "2-of-3 in order")
    vec(b"\x00" + push(sb) + push(sc), ms23, "", "OK", "2-of-3 later keys")
    vec(b"\x00" + push(sb) + push(sa), ms23, "", "eval-false",
        "2-of-3 out of order fails")
    vec(b"\x00" + push(sb) + push(sa), ms23, "NULLFAIL", "sig-nullfail",
        "out-of-order multisig under NULLFAIL")
    vec(b"\x00" + push(sa) + push(sa), ms23, "", "eval-false",
        "same sig twice fails")
    vec(pushnum(1) + push(sa) + push(sb), ms23, "NULLDUMMY", "sig-nulldummy",
        "non-null dummy under NULLDUMMY")
    vec(pushnum(1) + push(sa) + push(sb), ms23, "", "OK",
        "non-null dummy tolerated without NULLDUMMY")
    vec(b"\x00" + b"\x00" + b"\x00", ms23, "", "eval-false",
        "empty sigs fail 2-of-3 quietly")
    # CHECKMULTISIGVERIFY
    msv = S.multisig_script(1, [KEY.pubkey])[:-1] + op(
        OPC.OP_CHECKMULTISIGVERIFY, OPC.OP_1)
    sv = make_ctx_signature(msv, SIGHASH_ALL)
    vec(b"\x00" + push(sv), msv, "", "OK", "CHECKMULTISIGVERIFY valid")
    vec(b"\x00" + b"\x00", msv, "", "checkmultisigverify",
        "CHECKMULTISIGVERIFY failure")
    # pubkey/sig count bounds
    too_many = op(OPC.OP_1) + b"".join(push(KEY.pubkey) for _ in range(21)) + \
        push(_num(21)) + op(OPC.OP_CHECKMULTISIG)
    vec(b"\x00\x00", too_many, "", "pubkey-count", ">20 pubkeys")

    # ---- truthiness edges ----
    vec(push(b"\x80"), b"", "", "eval-false",
        "negative zero is false (cast_to_bool)")
    vec(push(b"\x00\x80"), b"", "", "eval-false",
        "multi-byte negative zero is false")
    vec(push(b"\x00\x01"), b"", "", "OK", "high zero byte with set bit is true")

    # ---- paired stack ops ----
    vec(pushnum(1) + pushnum(2) + pushnum(3) + pushnum(4),
        op(OPC.OP_2SWAP) + pushnum(2) + op(OPC.OP_EQUALVERIFY) + pushnum(1) +
        op(OPC.OP_EQUALVERIFY) + pushnum(4) + op(OPC.OP_EQUALVERIFY) +
        pushnum(3) + op(OPC.OP_EQUAL), "", "OK", "2SWAP order")
    vec(pushnum(1) + pushnum(2) + pushnum(3) + pushnum(4),
        op(OPC.OP_2OVER) + pushnum(2) + op(OPC.OP_EQUALVERIFY) + pushnum(1) +
        op(OPC.OP_EQUALVERIFY, OPC.OP_2DROP, OPC.OP_2DROP, OPC.OP_1),
        "", "OK", "2OVER copies bottom pair")
    vec(pushnum(1) + pushnum(2) + pushnum(3) + pushnum(4) + pushnum(5) +
        pushnum(6),
        op(OPC.OP_2ROT) + pushnum(2) + op(OPC.OP_EQUALVERIFY) + pushnum(1) +
        op(OPC.OP_EQUALVERIFY, OPC.OP_2DROP, OPC.OP_2DROP, OPC.OP_1),
        "", "OK", "2ROT rotates bottom pair to top")
    vec(pushnum(1), op(OPC.OP_2DUP), "", "invalid-stack-operation",
        "2DUP needs two")

    # ---- SIZE ----
    vec(push(b"\x01\x02\x03"), op(OPC.OP_SIZE, OPC.OP_3, OPC.OP_EQUALVERIFY,
                                  OPC.OP_DROP, OPC.OP_1), "", "OK",
        "SIZE of 3-byte push")
    vec(b"\x00", op(OPC.OP_SIZE, OPC.OP_0, OPC.OP_EQUALVERIFY, OPC.OP_DROP,
                    OPC.OP_1), "", "OK", "SIZE of empty push is 0")

    # ---- EQUALVERIFY failure code ----
    vec(pushnum(1) + pushnum(2), op(OPC.OP_EQUALVERIFY, OPC.OP_1), "",
        "equalverify", "EQUALVERIFY mismatch")

    # ---- NUMEQUALVERIFY ----
    vec(pushnum(3) + pushnum(3), op(OPC.OP_NUMEQUALVERIFY, OPC.OP_1), "",
        "OK", "NUMEQUALVERIFY pass")
    vec(pushnum(3) + pushnum(4), op(OPC.OP_NUMEQUALVERIFY, OPC.OP_1), "",
        "numequalverify", "NUMEQUALVERIFY fail")

    # ---- IFDUP on zero does not duplicate ----
    vec(pushnum(0), op(OPC.OP_IFDUP, OPC.OP_DEPTH, OPC.OP_1,
                       OPC.OP_EQUALVERIFY, OPC.OP_DROP, OPC.OP_1),
        "", "OK", "IFDUP leaves zero alone")

    # ---- numeric equivalence across encodings (NUMEQUAL vs EQUAL) ----
    vec(push(b"\x01\x00"), op(OPC.OP_1, OPC.OP_NUMEQUAL), "", "OK",
        "0x0100 numerically equals 1")
    vec(push(b"\x01\x00") + op(OPC.OP_1), op(OPC.OP_EQUAL), "", "eval-false",
        "0x0100 is not byte-equal to 0x01")

    # ---- op count limit (>201 non-push ops) ----
    many_ops = op(OPC.OP_1) + op(*([OPC.OP_DUP, OPC.OP_DROP] * 101))
    vec(b"", many_ops, "", "op-count", "202 ops exceeds MAX_OPS_PER_SCRIPT")
    # script size limit
    oversize = push(b"\x51" * 520) + op(OPC.OP_DROP)
    oversize = oversize * 20 + op(OPC.OP_1)  # > 10000 bytes
    vec(b"", oversize, "", "script-size", "script > 10000 bytes")


def gen_sighash_vectors(rng: random.Random, n: int = 120) -> list:
    """Random-tx digest vectors: [tx_hex, scriptCode_hex, in_idx, hashtype,
    amount, legacy_hex|None, forkid_hex]. Legacy is None for the FORKID
    hashtypes (undefined combination we never emit)."""
    out = []
    base_types = (SIGHASH_ALL, SIGHASH_NONE, SIGHASH_SINGLE)
    for _ in range(n):
        nin = rng.randint(1, 4)
        nout = rng.randint(0, 4)
        vin = tuple(
            CTxIn(
                COutPoint(rng.randbytes(32), rng.randint(0, 0xFFFF)),
                rng.randbytes(rng.randint(0, 40)),
                rng.choice((0xFFFFFFFF, 0xFFFFFFFE, 0, rng.randint(0, 1 << 31))),
            )
            for _ in range(nin)
        )
        vout = tuple(
            CTxOut(rng.randint(0, 21_000_000 * 100_000_000),
                   rng.randbytes(rng.randint(0, 48)))
            for _ in range(nout)
        )
        tx = CTransaction(
            version=rng.choice((1, 2)), vin=vin, vout=vout,
            locktime=rng.randint(0, 0xFFFFFFFF),
        )
        in_idx = rng.randrange(nin)
        # parseable script code: random pushes + simple ops, sometimes with
        # OP_CODESEPARATOR (which legacy sighash must strip)
        parts = []
        for _p in range(rng.randint(1, 4)):
            r = rng.random()
            if r < 0.5:
                parts.append(S.push_data_raw(rng.randbytes(rng.randint(0, 24))))
            elif r < 0.8:
                parts.append(bytes([rng.choice((S.OP_DUP, S.OP_HASH160,
                                                S.OP_EQUALVERIFY,
                                                S.OP_CHECKSIG, S.OP_NOP))]))
            else:
                parts.append(bytes([S.OP_CODESEPARATOR]))
        sc = b"".join(parts)
        amount = rng.randint(0, 21_000_000 * 100_000_000)
        ht = rng.choice(base_types) | rng.choice((0, SIGHASH_ANYONECANPAY))
        legacy = signature_hash_legacy(sc, tx, in_idx, ht)
        forkid = signature_hash_forkid(sc, tx, in_idx, ht | SIGHASH_FORKID,
                                       amount)
        out.append([tx.serialize().hex(), sc.hex(), in_idx, ht, amount,
                    legacy.hex(), forkid.hex()])
    # the SIGHASH_SINGLE out-of-range bug: digest is uint256(1)
    tx = CTransaction(
        version=1,
        vin=(CTxIn(COutPoint(b"\x11" * 32, 0), b"", 0xFFFFFFFF),
             CTxIn(COutPoint(b"\x22" * 32, 1), b"", 0xFFFFFFFF)),
        vout=(CTxOut(50_000, b"\x51"),),
        locktime=0,
    )
    legacy = signature_hash_legacy(b"\x51", tx, 1, SIGHASH_SINGLE)
    assert legacy == (1).to_bytes(32, "little"), "SIGHASH_SINGLE bug vector"
    out.append([tx.serialize().hex(), "51", 1, SIGHASH_SINGLE, 0,
                legacy.hex(),
                signature_hash_forkid(b"\x51", tx, 1,
                                      SIGHASH_SINGLE | SIGHASH_FORKID,
                                      0).hex()])
    return out


TX_VALID: list[dict] = []
TX_INVALID: list[dict] = []


def run_tx_vector(entry: dict) -> str:
    tx = CTransaction.deserialize(ByteReader(bytes.fromhex(entry["tx"])))
    if entry.get("mode") == "check":
        # CheckTransaction-level vector (src/test/data tx_invalid.json also
        # carries these: duplicate inputs, value overflow, empty vin/vout)
        from bitcoincashplus_tpu.consensus.tx_check import (
            TxValidationError,
            check_transaction,
        )

        try:
            check_transaction(tx)
            return "OK"
        except TxValidationError as e:
            return e.reason
    flags = parse_flags(entry["flags"])
    try:
        for i, (txin, (_h, _n, spk_hex, amount)) in enumerate(
            zip(tx.vin, entry["inputs"])
        ):
            checker = TransactionSignatureChecker(tx, i, amount)
            VerifyScript(txin.script_sig, bytes.fromhex(spk_hex), flags,
                         checker)
        return "OK"
    except ScriptError as e:
        return e.code


def tx_vec(valid: bool, inputs, tx: CTransaction, flags: str, expect: str,
           desc: str, mode: str = "script"):
    entry = {
        "inputs": [[h.hex(), n, spk.hex(), amount]
                   for (h, n, spk, amount) in inputs],
        "tx": tx.serialize().hex(),
        "flags": flags,
        "expect": expect,
        "desc": desc,
    }
    if mode != "script":
        entry["mode"] = mode
    got = run_tx_vector(entry)
    if got != expect:
        raise SystemExit(
            f"TX VECTOR MISMATCH: {desc!r}\n  expected {expect}, got {got}"
        )
    (TX_VALID if valid else TX_INVALID).append(entry)


def gen_tx_vectors():
    prev = b"\x77" * 32
    spk = KEY.p2pkh_script()
    amount = 5_000_000_000

    def spend_tx(nin=1, locktime=0, sequence=0xFFFFFFFF, value=None):
        vin = tuple(CTxIn(COutPoint(prev, i), b"", sequence)
                    for i in range(nin))
        vout = (CTxOut(value if value is not None else amount - 10_000,
                       b"\x51"),)
        return CTransaction(version=2, vin=vin, vout=vout, locktime=locktime)

    # valid P2PKH single input, forkid bundle
    tx = spend_tx()
    signed = sign_transaction(
        tx, [(spk, amount)], lambda i: KEY if i == KEY.pubkey_hash else None,
        enable_forkid=True,
    )
    tx_vec(True, [(prev, 0, spk, amount)], signed,
           "P2SH,STRICTENC,DERSIG,LOW_S,NULLFAIL,NULLDUMMY,FORKID", "OK",
           "P2PKH forkid spend, post-fork flag bundle")
    # same, legacy (pre-fork)
    signed_legacy = sign_transaction(
        tx, [(spk, amount)], lambda i: KEY if i == KEY.pubkey_hash else None,
        enable_forkid=False,
    )
    tx_vec(True, [(prev, 0, spk, amount)], signed_legacy, "P2SH", "OK",
           "P2PKH legacy spend, pre-fork flags")
    # two inputs
    tx2 = spend_tx(nin=2)
    signed2 = sign_transaction(
        tx2, [(spk, amount), (spk, amount)],
        lambda i: KEY if i == KEY.pubkey_hash else None, enable_forkid=True,
    )
    tx_vec(True, [(prev, 0, spk, amount), (prev, 1, spk, amount)], signed2,
           "P2SH,STRICTENC,NULLFAIL,FORKID", "OK", "two-input P2PKH spend")
    # P2SH multisig 2-of-3
    redeem = S.multisig_script(2, [KEY.pubkey, KEY2.pubkey, KEY3.pubkey])
    p2sh = S.p2sh_script_for_redeem(redeem)
    keymap = {KEY.pubkey: KEY, KEY2.pubkey: KEY2, KEY3.pubkey: KEY3}
    tx3 = spend_tx()
    signed3 = sign_transaction(
        tx3, [(p2sh, amount)], lambda i: keymap.get(i),
        enable_forkid=True, redeem_scripts={hash160(redeem): redeem},
    )
    tx_vec(True, [(prev, 0, p2sh, amount)], signed3,
           "P2SH,STRICTENC,NULLFAIL,NULLDUMMY,FORKID", "OK",
           "P2SH 2-of-3 multisig spend")
    # bare multisig
    ms = S.multisig_script(1, [KEY2.pubkey])
    tx4 = spend_tx()
    signed4 = sign_transaction(
        tx4, [(ms, amount)], lambda i: keymap.get(i), enable_forkid=True,
    )
    tx_vec(True, [(prev, 0, ms, amount)], signed4,
           "STRICTENC,NULLFAIL,NULLDUMMY,FORKID", "OK",
           "bare 1-of-1 multisig spend")
    # CLTV satisfied: tx locktime 500 >= required 400, sequence non-final
    cltv_spk = push(_num(400)) + op(S.OP_CHECKLOCKTIMEVERIFY, S.OP_DROP) + \
        push(KEY.pubkey) + op(S.OP_CHECKSIG)
    txl = spend_tx(locktime=500, sequence=0xFFFFFFFE)
    sig = make_signature(KEY, cltv_spk, txl, 0, amount,
                         SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    txl_signed = CTransaction(
        txl.version, (CTxIn(txl.vin[0].prevout, push(sig),
                            txl.vin[0].sequence),),
        txl.vout, txl.locktime,
    )
    tx_vec(True, [(prev, 0, cltv_spk, amount)], txl_signed,
           "CHECKLOCKTIMEVERIFY,FORKID,NULLFAIL", "OK", "CLTV satisfied")
    # CLTV unsatisfied: required 600 > locktime 500
    cltv_spk2 = push(_num(600)) + op(S.OP_CHECKLOCKTIMEVERIFY, S.OP_DROP) + \
        push(KEY.pubkey) + op(S.OP_CHECKSIG)
    sig2 = make_signature(KEY, cltv_spk2, txl, 0, amount,
                          SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    txl2 = CTransaction(
        txl.version, (CTxIn(txl.vin[0].prevout, push(sig2),
                            txl.vin[0].sequence),),
        txl.vout, txl.locktime,
    )
    tx_vec(False, [(prev, 0, cltv_spk2, amount)], txl2,
           "CHECKLOCKTIMEVERIFY,FORKID,NULLFAIL", "unsatisfied-locktime",
           "CLTV unsatisfied")
    # CSV satisfied: input sequence 20 relative blocks, spk requires 10
    csv_spk = push(_num(10)) + op(S.OP_CHECKSEQUENCEVERIFY, S.OP_DROP) + \
        push(KEY.pubkey) + op(S.OP_CHECKSIG)
    txs = spend_tx(sequence=20)  # version 2, type flag clear -> blocks
    sigs_ = make_signature(KEY, csv_spk, txs, 0, amount,
                           SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    txs_signed = CTransaction(
        txs.version, (CTxIn(txs.vin[0].prevout, push(sigs_), 20),),
        txs.vout, txs.locktime,
    )
    tx_vec(True, [(prev, 0, csv_spk, amount)], txs_signed,
           "CHECKSEQUENCEVERIFY,FORKID,NULLFAIL", "OK", "CSV satisfied")
    # CSV unsatisfied: requires 30, sequence 20
    csv_spk2 = push(_num(30)) + op(S.OP_CHECKSEQUENCEVERIFY, S.OP_DROP) + \
        push(KEY.pubkey) + op(S.OP_CHECKSIG)
    sig3 = make_signature(KEY, csv_spk2, txs, 0, amount,
                          SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    txs2 = CTransaction(
        txs.version, (CTxIn(txs.vin[0].prevout, push(sig3), 20),),
        txs.vout, txs.locktime,
    )
    tx_vec(False, [(prev, 0, csv_spk2, amount)], txs2,
           "CHECKSEQUENCEVERIFY,FORKID,NULLFAIL", "unsatisfied-locktime",
           "CSV unsatisfied")
    # wrong-amount forkid signature
    signed_bad = sign_transaction(
        spend_tx(), [(spk, amount + 1)],
        lambda i: KEY if i == KEY.pubkey_hash else None, enable_forkid=True,
    )
    tx_vec(False, [(prev, 0, spk, amount)], signed_bad,
           "STRICTENC,NULLFAIL,FORKID", "sig-nullfail",
           "forkid sig commits to amount; mismatch fails")
    # unsigned spend
    tx_vec(False, [(prev, 0, spk, amount)], spend_tx(),
           "STRICTENC,NULLFAIL,FORKID", "invalid-stack-operation",
           "unsigned P2PKH spend")
    # missing FORKID bit under post-fork flags
    tx_vec(False, [(prev, 0, spk, amount)], signed_legacy,
           "STRICTENC,NULLFAIL,FORKID", "must-use-forkid",
           "legacy sig rejected post-fork")


def gen_tx_matrix_vectors():
    """Reference-scale tx corpus (src/test/data/tx_valid.json carries
    hundreds of entries): programmatic matrices over sighash types,
    locktime/sequence boundaries, FindAndDelete/CODESEPARATOR, hybrid
    pubkeys, flag boundaries, legacy-vs-FORKID pairs, multisig shapes, and
    CheckTransaction-level structural rules."""
    prev = b"\x77" * 32
    spk = KEY.p2pkh_script()
    amount = 5_000_000_000
    keymap = {KEY.pubkey: KEY, KEY2.pubkey: KEY2, KEY3.pubkey: KEY3,
              KEY.pubkey_hash: KEY, KEY2.pubkey_hash: KEY2,
              KEY3.pubkey_hash: KEY3}

    def spend_tx(nin=1, locktime=0, sequence=0xFFFFFFFF, value=None,
                 version=2):
        vin = tuple(CTxIn(COutPoint(prev, i), b"", sequence)
                    for i in range(nin))
        vout = (CTxOut(value if value is not None else amount - 10_000,
                       b"\x51"),)
        return CTransaction(version=version, vin=vin, vout=vout,
                            locktime=locktime)

    def signed_p2pkh(tx, hashtype, forkid, n_inputs=1, amounts=None):
        amounts = amounts or [amount] * n_inputs
        return sign_transaction(
            tx, [(spk, a) for a in amounts], lambda i: keymap.get(i),
            hashtype=hashtype, enable_forkid=forkid,
        )

    # ---- 1. sighash-type matrix: every base type x ACP x forkid/legacy,
    # one- and two-input forms (SIGHASH_SINGLE needs vout coverage) -------
    for base_name, base_ht in (("ALL", SIGHASH_ALL), ("NONE", SIGHASH_NONE),
                               ("SINGLE", SIGHASH_SINGLE)):
        for acp in (0, SIGHASH_ANYONECANPAY):
            for forkid in (True, False):
                for nin in (1, 2):
                    if base_ht == SIGHASH_SINGLE and nin == 2:
                        # vout[1] must exist for input 1: give the tx 2 outs
                        tx = CTransaction(
                            version=2,
                            vin=tuple(CTxIn(COutPoint(prev, i), b"",
                                            0xFFFFFFFF) for i in range(2)),
                            vout=(CTxOut(1000, b"\x51"),
                                  CTxOut(2000, b"\x51")),
                        )
                    else:
                        tx = spend_tx(nin=nin)
                    ht = base_ht | acp
                    signed = signed_p2pkh(tx, ht, forkid, nin)
                    flags = ("P2SH,STRICTENC,NULLFAIL"
                             + (",FORKID" if forkid else ""))
                    tx_vec(True,
                           [(prev, i, spk, amount) for i in range(nin)],
                           signed, flags, "OK",
                           f"sighash {base_name}"
                           f"{'|ACP' if acp else ''} "
                           f"{'forkid' if forkid else 'legacy'} {nin}-in")

    # ---- 2. CLTV boundary matrix ---------------------------------------
    thresh = 500_000_000  # LOCKTIME_THRESHOLD

    def cltv_case(required, locktime, sequence, ok, why):
        cspk = push(_num(required)) + op(S.OP_CHECKLOCKTIMEVERIFY,
                                         S.OP_DROP) + \
            push(KEY.pubkey) + op(S.OP_CHECKSIG)
        tx = spend_tx(locktime=locktime, sequence=sequence)
        sig = make_signature(KEY, cspk, tx, 0, amount,
                             SIGHASH_ALL | SIGHASH_FORKID,
                             enable_forkid=True)
        tx = CTransaction(tx.version,
                          (CTxIn(tx.vin[0].prevout, push(sig), sequence),),
                          tx.vout, tx.locktime)
        tx_vec(ok, [(prev, 0, cspk, amount)], tx,
               "CHECKLOCKTIMEVERIFY,FORKID,NULLFAIL",
               "OK" if ok else "unsatisfied-locktime", f"CLTV {why}")

    cltv_case(400, 400, 0xFFFFFFFE, True, "exactly equal heights")
    cltv_case(400, 401, 0xFFFFFFFE, True, "locktime above requirement")
    cltv_case(401, 400, 0xFFFFFFFE, False, "one short")
    cltv_case(0, 0, 0xFFFFFFFE, True, "zero requirement")
    cltv_case(thresh, thresh, 0xFFFFFFFE, True, "time-type equal")
    cltv_case(thresh - 1, thresh, 0xFFFFFFFE, False,
              "height-type vs time-type mismatch")
    cltv_case(thresh, thresh - 1, 0xFFFFFFFE, False,
              "time-type vs height-type mismatch")
    cltv_case(400, 500, 0xFFFFFFFF, False, "final sequence disables CLTV")

    # ---- 3. CSV boundary matrix ----------------------------------------
    type_flag = 0x00400000  # SEQUENCE_LOCKTIME_TYPE_FLAG (time-based)
    disable = 0x80000000

    def csv_case(required, sequence, ok, why, version=2, code=None):
        cspk = push(_num(required)) + op(S.OP_CHECKSEQUENCEVERIFY,
                                         S.OP_DROP) + \
            push(KEY.pubkey) + op(S.OP_CHECKSIG)
        tx = spend_tx(sequence=sequence, version=version)
        sig = make_signature(KEY, cspk, tx, 0, amount,
                             SIGHASH_ALL | SIGHASH_FORKID,
                             enable_forkid=True)
        tx = CTransaction(tx.version,
                          (CTxIn(tx.vin[0].prevout, push(sig), sequence),),
                          tx.vout, tx.locktime)
        tx_vec(ok, [(prev, 0, cspk, amount)], tx,
               "CHECKSEQUENCEVERIFY,FORKID,NULLFAIL",
               "OK" if ok else (code or "unsatisfied-locktime"),
               f"CSV {why}")

    csv_case(10, 10, True, "blocks exactly equal")
    csv_case(10, 11, True, "blocks above")
    csv_case(11, 10, False, "blocks one short")
    csv_case(10, 10, False, "version 1 rejects CSV", version=1)
    csv_case(type_flag | 5, type_flag | 5, True, "time-type equal")
    csv_case(type_flag | 5, 5, False, "type mismatch time-vs-blocks")
    csv_case(5, type_flag | 5, False, "type mismatch blocks-vs-time")
    csv_case(10, disable | 10, False, "disable flag voids the check")

    # ---- 4. FindAndDelete / CODESEPARATOR ------------------------------
    # scriptCode signing with a CODESEPARATOR: only the tail past the LAST
    # executed separator is committed (legacy), and pushes equal to the
    # signature are stripped (FindAndDelete) before hashing
    cs_spk = push(KEY.pubkey) + op(S.OP_CODESEPARATOR, S.OP_CHECKSIG)
    tx = spend_tx()
    # sign against the post-separator tail (interpreter starts scriptCode
    # at the last executed separator)
    tail = push(KEY.pubkey)[0:0] + op(S.OP_CHECKSIG)
    sig = make_signature(KEY, tail, tx, 0, amount,
                         SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    tx_cs = CTransaction(tx.version,
                         (CTxIn(tx.vin[0].prevout, push(sig), 0xFFFFFFFF),),
                         tx.vout, tx.locktime)
    tx_vec(True, [(prev, 0, cs_spk, amount)], tx_cs,
           "FORKID,NULLFAIL", "OK",
           "CODESEPARATOR: sig commits to post-separator tail")
    # signing the WHOLE script instead must fail
    sig_whole = make_signature(KEY, cs_spk, tx, 0, amount,
                               SIGHASH_ALL | SIGHASH_FORKID,
                               enable_forkid=True)
    tx_cs2 = CTransaction(tx.version,
                          (CTxIn(tx.vin[0].prevout, push(sig_whole),
                                 0xFFFFFFFF),),
                          tx.vout, tx.locktime)
    tx_vec(False, [(prev, 0, cs_spk, amount)], tx_cs2,
           "FORKID,NULLFAIL", "sig-nullfail",
           "CODESEPARATOR: whole-script sig rejected")
    # legacy FindAndDelete: a scriptPubKey embedding the signature push —
    # the legacy sighash strips PUSH(sig) from scriptCode before hashing,
    # so the sig is made against the STRIPPED form (breaking the circular
    # dependency: the stripped scriptCode doesn't contain the sig)
    tx_fd = spend_tx()
    stripped = op(S.OP_DROP) + push(KEY.pubkey) + op(S.OP_CHECKSIG)
    sig_fd = make_signature(KEY, stripped, tx_fd, 0, amount, SIGHASH_ALL,
                            enable_forkid=False)
    fd_spk = push(sig_fd) + stripped
    tx_fd2 = CTransaction(tx_fd.version,
                          (CTxIn(tx_fd.vin[0].prevout, push(sig_fd),
                                 0xFFFFFFFF),),
                          tx_fd.vout, tx_fd.locktime)
    tx_vec(True, [(prev, 0, fd_spk, amount)], tx_fd2,
           "NULLFAIL", "OK",
           "FindAndDelete: sig push embedded in scriptPubKey is stripped")

    # ---- 5. hybrid pubkeys under STRICTENC -----------------------------
    pt = secp.pubkey_parse(KEY.pubkey)
    hybrid = bytes([6 + (pt[1] & 1)]) + pt[0].to_bytes(32, "big") + \
        pt[1].to_bytes(32, "big")
    hspk = push(hybrid) + op(S.OP_CHECKSIG)
    tx_h = spend_tx()
    sig_h = make_signature(KEY, hspk, tx_h, 0, amount,
                           SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    tx_h2 = CTransaction(tx_h.version,
                         (CTxIn(tx_h.vin[0].prevout, push(sig_h),
                                0xFFFFFFFF),),
                         tx_h.vout, tx_h.locktime)
    tx_vec(True, [(prev, 0, hspk, amount)], tx_h2,
           "FORKID,NULLFAIL", "OK", "hybrid pubkey accepted pre-STRICTENC")
    tx_vec(False, [(prev, 0, hspk, amount)], tx_h2,
           "FORKID,NULLFAIL,STRICTENC", "pubkeytype",
           "hybrid pubkey rejected under STRICTENC")

    # ---- 6. flag boundaries: LOW_S / NULLDUMMY / NULLFAIL --------------
    tx_s = spend_tx()
    ehash = None
    sig_lowS = make_signature(KEY, spk, tx_s, 0, amount,
                              SIGHASH_ALL | SIGHASH_FORKID,
                              enable_forkid=True)
    # reconstruct a high-S twin of the same signature
    r_v, s_v = secp.sig_der_decode(sig_lowS[:-1])
    sig_highS = secp.sig_der_encode(r_v, secp.N - s_v) + sig_lowS[-1:]
    for sig_v, flags, ok, code, why in (
        (sig_highS, "FORKID,NULLFAIL", True, "OK",
         "high-S accepted without LOW_S"),
        (sig_highS, "FORKID,NULLFAIL,LOW_S", False, "sig-high-s",
         "high-S rejected under LOW_S"),
    ):
        txv = CTransaction(tx_s.version,
                           (CTxIn(tx_s.vin[0].prevout,
                                  push(sig_v) + push(KEY.pubkey),
                                  0xFFFFFFFF),),
                           tx_s.vout, tx_s.locktime)
        tx_vec(ok, [(prev, 0, spk, amount)], txv, flags, code, why)
    del ehash
    # NULLDUMMY: multisig dummy must be empty when flagged
    ms_spk = S.multisig_script(1, [KEY.pubkey])
    tx_m = spend_tx()
    sig_m = make_signature(KEY, ms_spk, tx_m, 0, amount,
                           SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    for dummy, flags, ok, code, why in (
        (op(S.OP_1), "FORKID,NULLFAIL", True, "OK",
         "non-null multisig dummy tolerated without NULLDUMMY"),
        (op(S.OP_1), "FORKID,NULLFAIL,NULLDUMMY", False, "sig-nulldummy",
         "non-null multisig dummy rejected under NULLDUMMY"),
    ):
        txv = CTransaction(tx_m.version,
                           (CTxIn(tx_m.vin[0].prevout, dummy + push(sig_m),
                                  0xFFFFFFFF),),
                           tx_m.vout, tx_m.locktime)
        tx_vec(ok, [(prev, 0, ms_spk, amount)], txv, flags, code, why)
    # NULLFAIL: a failing CHECKSIG with a NON-empty sig
    tx_f = spend_tx()
    sig_f = make_signature(KEY2, spk, tx_f, 0, amount,
                           SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    txv = CTransaction(tx_f.version,
                       (CTxIn(tx_f.vin[0].prevout,
                              push(sig_f) + push(KEY.pubkey), 0xFFFFFFFF),),
                       tx_f.vout, tx_f.locktime)
    tx_vec(False, [(prev, 0, spk, amount)], txv, "FORKID,NULLFAIL",
           "sig-nullfail", "wrong-key sig under NULLFAIL")
    tx_vec(False, [(prev, 0, spk, amount)], txv, "FORKID",
           "eval-false", "wrong-key sig without NULLFAIL fails at the end")

    # ---- 7. legacy-vs-FORKID pairs -------------------------------------
    tx_p = spend_tx()
    signed_forkid = signed_p2pkh(tx_p, SIGHASH_ALL, True)
    signed_legacy = signed_p2pkh(tx_p, SIGHASH_ALL, False)
    tx_vec(False, [(prev, 0, spk, amount)], signed_forkid,
           "STRICTENC,NULLFAIL", "illegal-forkid",
           "forkid-bit sig rejected under legacy STRICTENC")
    tx_vec(True, [(prev, 0, spk, amount)], signed_forkid,
           "STRICTENC,NULLFAIL,FORKID", "OK",
           "forkid sig accepted post-fork")
    tx_vec(False, [(prev, 0, spk, amount)], signed_legacy,
           "STRICTENC,NULLFAIL,FORKID", "must-use-forkid",
           "legacy sig rejected post-fork (replay protection)")
    tx_vec(True, [(prev, 0, spk, amount)], signed_legacy,
           "STRICTENC,NULLFAIL", "OK", "legacy sig accepted pre-fork")

    # ---- 8. multisig shapes --------------------------------------------
    for m, keys, why in (
        (1, [KEY, KEY2], "1-of-2"),
        (2, [KEY, KEY2], "2-of-2"),
        (2, [KEY, KEY2, KEY3], "2-of-3"),
        (3, [KEY, KEY2, KEY3], "3-of-3"),
    ):
        msk = S.multisig_script(m, [k.pubkey for k in keys])
        tx_n = spend_tx()
        signed = sign_transaction(tx_n, [(msk, amount)],
                                  lambda i: keymap.get(i),
                                  enable_forkid=True)
        tx_vec(True, [(prev, 0, msk, amount)], signed,
               "FORKID,NULLFAIL,NULLDUMMY", "OK", f"bare multisig {why}")
    # out-of-order sigs fail (CHECKMULTISIG is order-sensitive)
    msk = S.multisig_script(2, [KEY.pubkey, KEY2.pubkey])
    tx_o = spend_tx()
    s1 = make_signature(KEY, msk, tx_o, 0, amount,
                        SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    s2 = make_signature(KEY2, msk, tx_o, 0, amount,
                        SIGHASH_ALL | SIGHASH_FORKID, enable_forkid=True)
    tx_o2 = CTransaction(tx_o.version,
                         (CTxIn(tx_o.vin[0].prevout,
                                b"\x00" + push(s2) + push(s1), 0xFFFFFFFF),),
                         tx_o.vout, tx_o.locktime)
    tx_vec(False, [(prev, 0, msk, amount)], tx_o2,
           "FORKID,NULLFAIL,NULLDUMMY", "sig-nullfail",
           "multisig out-of-order sigs rejected")

    # ---- 9. CheckTransaction structural matrix (mode=check) ------------
    def raw_tx(vin, vout, version=1, locktime=0):
        return CTransaction(version=version, vin=tuple(vin),
                            vout=tuple(vout), locktime=locktime)

    inp = CTxIn(COutPoint(prev, 0), b"\x51", 0xFFFFFFFF)
    out1 = CTxOut(1000, b"\x51")
    MAXM = 21_000_000 * 100_000_000
    tx_vec(True, [], raw_tx([inp], [out1]), "", "OK",
           "minimal structurally-valid tx", mode="check")
    tx_vec(True, [], raw_tx([inp], [CTxOut(MAXM, b"\x51")]), "", "OK",
           "single output at exactly MAX_MONEY", mode="check")
    tx_vec(False, [], raw_tx([], [out1]), "", "bad-txns-vin-empty",
           "empty vin", mode="check")
    tx_vec(False, [], raw_tx([inp], []), "", "bad-txns-vout-empty",
           "empty vout", mode="check")
    tx_vec(False, [], raw_tx([inp], [CTxOut(-1, b"\x51")]), "",
           "bad-txns-vout-negative", "negative output value", mode="check")
    tx_vec(False, [], raw_tx([inp], [CTxOut(MAXM + 1, b"\x51")]), "",
           "bad-txns-vout-toolarge", "output above MAX_MONEY", mode="check")
    tx_vec(False, [],
           raw_tx([inp], [CTxOut(MAXM, b"\x51"), CTxOut(1, b"\x51")]), "",
           "bad-txns-txouttotal-toolarge", "output SUM above MAX_MONEY",
           mode="check")
    tx_vec(False, [],
           raw_tx([inp, CTxIn(COutPoint(prev, 0), b"\x52", 0)], [out1]),
           "", "bad-txns-inputs-duplicate", "duplicate prevouts",
           mode="check")
    tx_vec(False, [],
           raw_tx([CTxIn(COutPoint(), b"\x51" * 51, 0xFFFFFFFF), inp],
                  [out1]),
           "", "bad-txns-prevout-null",
           "null prevout in non-coinbase (2 inputs)", mode="check")
    tx_vec(True, [],
           raw_tx([CTxIn(COutPoint(), b"\x51" * 51, 0xFFFFFFFF)], [out1]),
           "", "OK", "coinbase with in-range scriptSig", mode="check")
    tx_vec(False, [],
           raw_tx([CTxIn(COutPoint(), b"\x51", 0xFFFFFFFF)], [out1]),
           "", "bad-cb-length", "coinbase scriptSig too short",
           mode="check")
    tx_vec(False, [],
           raw_tx([CTxIn(COutPoint(), b"\x51" * 101, 0xFFFFFFFF)], [out1]),
           "", "bad-cb-length", "coinbase scriptSig too long", mode="check")

    # ---- 10. randomized spend matrix: P2PKH/P2PK/P2SH-multisig spends,
    # random input counts / sighash types, each emitted in a valid form AND
    # a mutated-invalid twin (signature bit-flip, wrong amount, or wrong
    # hashtype byte) — reference-scale bulk with asserted expectations ----
    rng = random.Random(0xF00D)
    keys = [KEY, KEY2, KEY3]
    for case in range(72):
        nin = rng.choice((1, 1, 2, 3))
        kind = rng.choice(("p2pkh", "p2pk", "p2sh"))
        key = keys[case % 3]
        if kind == "p2pkh":
            spk_c = key.p2pkh_script()
            redeems = None
        elif kind == "p2pk":
            spk_c = push(key.pubkey) + op(S.OP_CHECKSIG)
            redeems = None
        else:
            m = rng.choice((1, 2))
            redeem = S.multisig_script(m, [k.pubkey for k in keys[:m + 1]])
            spk_c = S.p2sh_script_for_redeem(redeem)
            redeems = {hash160(redeem): redeem}
        ht = rng.choice((SIGHASH_ALL, SIGHASH_NONE, SIGHASH_SINGLE))
        if ht == SIGHASH_SINGLE:
            nin = 1  # keep vout coverage trivial
        ht |= rng.choice((0, SIGHASH_ANYONECANPAY))
        amt = rng.randint(546, 21_000_000 * 100_000_000 // 2)
        tx_r = CTransaction(
            version=2,
            vin=tuple(CTxIn(COutPoint(prev, i), b"", 0xFFFFFFFE)
                      for i in range(nin)),
            vout=(CTxOut(max(amt - 10_000, 546), b"\x51"),),
        )
        signed = sign_transaction(
            tx_r, [(spk_c, amt)] * nin, lambda i: keymap.get(i),
            hashtype=ht, enable_forkid=True, redeem_scripts=redeems,
        )
        flags = "P2SH,STRICTENC,NULLFAIL,NULLDUMMY,FORKID"
        desc = f"matrix #{case}: {kind} {nin}-in ht={ht:#x} amt={amt}"
        tx_vec(True, [(prev, i, spk_c, amt) for i in range(nin)], signed,
               flags, "OK", desc)
        # invalid twin
        mutation = rng.choice(("flip", "amount", "hashtype"))
        if kind == "p2sh" and mutation == "hashtype":
            mutation = "flip"  # scriptSig starts with the OP_0 dummy, not a sig push
        if mutation == "flip":
            sig0 = bytearray(signed.vin[0].script_sig)
            # flip a bit inside the DER body (skip the push opcode)
            sig0[5] ^= 0x01
            bad = CTransaction(
                signed.version,
                (CTxIn(signed.vin[0].prevout, bytes(sig0),
                       signed.vin[0].sequence),) + signed.vin[1:],
                signed.vout, signed.locktime,
            )
            codes = {"sig-nullfail", "sig-der", "bad-der-encoding",
                     "pubkeytype"}
        elif mutation == "amount":
            bad = signed
            codes = {"sig-nullfail", "equalverify", "eval-false"}
            # evaluate against a different credited amount
            got = run_tx_vector({
                "inputs": [[prev.hex(), i, spk_c.hex(), amt + 1]
                           for i in range(nin)],
                "tx": bad.serialize().hex(), "flags": flags,
                "expect": "?", "desc": desc, "mode": "script"})
            assert got in codes, (desc, got)
            entry = {
                "inputs": [[prev.hex(), i, spk_c.hex(), amt + 1]
                           for i in range(nin)],
                "tx": bad.serialize().hex(), "flags": flags,
                "expect": got, "desc": desc + " [wrong amount]",
            }
            TX_INVALID.append(entry)
            continue
        else:
            sig0 = bytearray(signed.vin[0].script_sig)
            sig_len = sig0[0]
            sig0[sig_len] = 0x23  # hashtype byte -> undefined base type
            # (p2pkh/p2pk only: byte 0 is the signature push length)
            bad = CTransaction(
                signed.version,
                (CTxIn(signed.vin[0].prevout, bytes(sig0),
                       signed.vin[0].sequence),) + signed.vin[1:],
                signed.vout, signed.locktime,
            )
            codes = {"sig-hashtype", "sig-nullfail"}
        got = run_tx_vector({
            "inputs": [[prev.hex(), i, spk_c.hex(), amt]
                       for i in range(nin)],
            "tx": bad.serialize().hex(), "flags": flags,
            "expect": "?", "desc": desc, "mode": "script"})
        assert got in codes and got != "OK", (desc, mutation, got)
        TX_INVALID.append({
            "inputs": [[prev.hex(), i, spk_c.hex(), amt]
                       for i in range(nin)],
            "tx": bad.serialize().hex(), "flags": flags,
            "expect": got, "desc": desc + f" [{mutation}]",
        })


def main():
    os.makedirs(DATA_DIR, exist_ok=True)
    rng = random.Random(0xBC9)

    gen_script_vectors()
    sighash = gen_sighash_vectors(rng)
    gen_tx_vectors()
    gen_tx_matrix_vectors()

    def dump(name, comment, payload):
        path = os.path.join(DATA_DIR, name)
        with open(path, "w") as f:
            json.dump([comment] + payload, f, indent=0)
            f.write("\n")
        print(f"wrote {path}: {len(payload)} vectors")

    dump("script_tests.json",
         "[scriptSig_hex, scriptPubKey_hex, flags, expect, desc] — "
         "generated by tools/gen_vectors.py; do not hand-edit",
         SCRIPT_VECTORS)
    dump("sighash.json",
         "[tx_hex, scriptCode_hex, in_idx, hashtype, amount, legacy_hex, "
         "forkid_hex] — generated by tools/gen_vectors.py",
         sighash)
    dump("tx_valid.json",
         "{inputs, tx, flags, expect, desc} — generated by tools/gen_vectors.py",
         TX_VALID)
    dump("tx_invalid.json",
         "{inputs, tx, flags, expect, desc} — generated by tools/gen_vectors.py",
         TX_INVALID)


if __name__ == "__main__":
    main()
