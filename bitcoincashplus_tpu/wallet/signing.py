"""Transaction signing — the solver/sign glue.

Reference: src/script/sign.cpp (ProduceSignature, SignSignature, Solver
dispatch on script template). Supports P2PKH, P2PK, and P2SH-wrapped
multisig — the templates the node's own tests and wallet emit.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..consensus.tx import CTransaction, CTxIn
from ..script.script import (
    classify_script,
    get_script_ops,
    push_data_raw,
)
from ..script.sighash import (
    SIGHASH_ALL,
    SIGHASH_FORKID,
    SighashCache,
    signature_hash,
)
from .keys import CKey


class SignError(Exception):
    pass


def make_signature(
    key: CKey,
    script_code: bytes,
    tx: CTransaction,
    in_idx: int,
    amount: int,
    hashtype: int = SIGHASH_ALL,
    *,
    enable_forkid: bool = False,
    cache: Optional[SighashCache] = None,
) -> bytes:
    """One input signature: DER + 1-byte hashtype (sign.cpp ProduceSignature
    inner Sign1). Pass hashtype WITHOUT the forkid bit; it is added when
    enable_forkid is set (TransactionSignatureCreator does the same)."""
    if enable_forkid:
        hashtype |= SIGHASH_FORKID
    ehash = signature_hash(
        script_code, tx, in_idx, hashtype, amount,
        enable_forkid=enable_forkid, cache=cache,
    )
    return key.sign(ehash) + bytes([hashtype & 0xFF])


def solve_script_sig(
    script_pubkey: bytes,
    tx: CTransaction,
    in_idx: int,
    amount: int,
    key_for_id: Callable[[bytes], Optional[CKey]],
    hashtype: int = SIGHASH_ALL,
    *,
    enable_forkid: bool = False,
    redeem_script: Optional[bytes] = None,
    cache: Optional[SighashCache] = None,
) -> bytes:
    """Build a scriptSig for one input (sign.cpp SignStep).

    ``key_for_id`` maps a pubkey-hash (for pubkeyhash) or raw pubkey (for
    pubkey/multisig) to a CKey, or None if unknown.
    """
    kind = classify_script(script_pubkey)
    if kind == "pubkeyhash":
        ops = list(get_script_ops(script_pubkey))
        pkh = ops[2][1]
        key = key_for_id(pkh)
        if key is None:
            raise SignError("missing key for pubkeyhash")
        sig = make_signature(
            key, script_pubkey, tx, in_idx, amount, hashtype,
            enable_forkid=enable_forkid, cache=cache,
        )
        return push_data_raw(sig) + push_data_raw(key.pubkey)
    if kind == "pubkey":
        ops = list(get_script_ops(script_pubkey))
        pubkey = ops[0][1]
        key = key_for_id(pubkey)
        if key is None:
            raise SignError("missing key for pubkey")
        sig = make_signature(
            key, script_pubkey, tx, in_idx, amount, hashtype,
            enable_forkid=enable_forkid, cache=cache,
        )
        return push_data_raw(sig)
    if kind == "multisig":
        ops = list(get_script_ops(script_pubkey))
        m = ops[0][0] - 0x50
        sigs = []
        for _, pubkey, _ in ops[1:-2]:
            if len(sigs) == m:
                break
            key = key_for_id(pubkey)
            if key is None:
                continue
            sigs.append(
                make_signature(
                    key, script_pubkey, tx, in_idx, amount, hashtype,
                    enable_forkid=enable_forkid, cache=cache,
                )
            )
        if len(sigs) < m:
            raise SignError(f"only {len(sigs)} of {m} multisig keys known")
        out = b"\x00"  # OP_0 dummy (CHECKMULTISIG off-by-one)
        for sig in sigs:
            out += push_data_raw(sig)
        return out
    if kind == "scripthash":
        if redeem_script is None:
            raise SignError("missing redeem script for P2SH input")
        inner = solve_script_sig(
            redeem_script, tx, in_idx, amount, key_for_id, hashtype,
            enable_forkid=enable_forkid, cache=cache,
        )
        return inner + push_data_raw(redeem_script)
    raise SignError(f"cannot sign {kind} script")


def sign_transaction(
    tx: CTransaction,
    spent_outputs: list,  # list of (script_pubkey, amount) per input
    key_for_id: Callable[[bytes], Optional[CKey]],
    hashtype: int = SIGHASH_ALL,
    *,
    enable_forkid: bool = False,
    redeem_scripts: Optional[dict[bytes, bytes]] = None,  # hash160 -> script
) -> CTransaction:
    """SignSignature over every input; returns a new signed CTransaction.

    Signatures commit to the final scriptSig-free layout, so the unsigned
    ``tx`` must already have its full vin/vout; scriptSigs are replaced.
    """
    assert len(spent_outputs) == len(tx.vin)
    cache = SighashCache(tx)
    new_vin = []
    for i, (txin, (spk, amount)) in enumerate(zip(tx.vin, spent_outputs)):
        redeem = None
        if redeem_scripts and classify_script(spk) == "scripthash":
            redeem = redeem_scripts.get(spk[2:22])
        script_sig = solve_script_sig(
            spk, tx, i, amount, key_for_id, hashtype,
            enable_forkid=enable_forkid, redeem_script=redeem, cache=cache,
        )
        new_vin.append(CTxIn(txin.prevout, script_sig, txin.sequence))
    return CTransaction(tx.version, tuple(new_vin), tx.vout, tx.locktime)
