"""Wallet-lite: keys, addresses, transaction signing, coin tracking.

Reference: src/wallet/ (CWallet — ~9k LoC of BDB-backed key management,
coin selection, and signing). This is the capability-parity subset
(SURVEY.md §3.1 "minimal wallet"): enough to mine to an address, track
owned coins, and build/sign spends for e2e tests and RPC — no BDB, no
encryption, no HD gap-limit machinery.
"""

from .keys import CKey, address_to_script, script_to_address  # noqa: F401
from .signing import sign_transaction, SignError  # noqa: F401
from .wallet import Wallet  # noqa: F401
