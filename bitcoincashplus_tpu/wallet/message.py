"""Signed messages — the "Bitcoin Signed Message" scheme.

Reference: src/util/message semantics live in rpcwallet.cpp/misc.cpp in the
v0.14 lineage (signmessage / verifymessage handlers) with the magic string
from CChainParams::strMessageMagic ("Bitcoin Signed Message:\n") and
CKey::SignCompact / CPubKey::RecoverCompact (src/key.cpp, src/pubkey.cpp).

Wire format: base64 of 65 bytes — header byte (27 + recid, +4 when the
signing key is compressed) then r and s as 32-byte big-endian scalars.
"""

from __future__ import annotations

import base64
from typing import Optional

from ..consensus.params import ChainParams
from ..consensus.serialize import ser_compact_size
from ..crypto import secp256k1 as secp
from ..crypto.hashes import hash160, sha256d
from .keys import CKey

MESSAGE_MAGIC = b"Bitcoin Signed Message:\n"


def message_hash(message: str) -> bytes:
    """CHashWriter << strMessageMagic << strMessage (both length-prefixed
    like string serialization), double-SHA256."""
    msg = message.encode("utf-8")
    data = (ser_compact_size(len(MESSAGE_MAGIC)) + MESSAGE_MAGIC
            + ser_compact_size(len(msg)) + msg)
    return sha256d(data)


def sign_message(key: CKey, message: str) -> str:
    """CKey::SignCompact over the message hash, base64-encoded."""
    e = int.from_bytes(message_hash(message), "big")
    r, s, recid = secp.ecdsa_sign_recoverable(key.secret, e)
    header = 27 + recid + (4 if key.compressed else 0)
    blob = bytes([header]) + r.to_bytes(32, "big") + s.to_bytes(32, "big")
    return base64.b64encode(blob).decode("ascii")


def recover_pubkey(signature_b64: str, message: str) -> Optional[bytes]:
    """CPubKey::RecoverCompact — returns the serialized pubkey (in the
    compressed/uncompressed form the header byte claims), or None."""
    try:
        blob = base64.b64decode(signature_b64, validate=True)
    except Exception:
        return None
    if len(blob) != 65:
        return None
    header = blob[0]
    if not (27 <= header < 35):
        return None
    compressed = header >= 31
    recid = (header - 27) & 3
    r = int.from_bytes(blob[1:33], "big")
    s = int.from_bytes(blob[33:65], "big")
    e = int.from_bytes(message_hash(message), "big")
    pt = secp.ecdsa_recover(r, s, recid, e)
    if pt is None:
        return None
    return secp.pubkey_serialize(pt, compressed)


def verify_message(address: str, signature_b64: str, message: str,
                   params: ChainParams) -> bool:
    """verifymessage: recovered-key hash must equal the address's key hash
    (only P2PKH addresses identify a key)."""
    from ..crypto.base58 import b58check_decode

    payload = b58check_decode(address)
    if payload is None or len(payload) != 21:
        return False
    if payload[0] != params.pubkey_addr_prefix:
        return False
    pubkey = recover_pubkey(signature_b64, message)
    if pubkey is None:
        return False
    return hash160(pubkey) == payload[1:]
