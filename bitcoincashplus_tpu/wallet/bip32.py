"""BIP32 hierarchical deterministic keys.

Reference: src/key.cpp (CExtKey::Derive), src/pubkey.cpp (CExtPubKey::
Derive), src/bip32.h path helpers; the reference wallet derives keypool
keys at m/0'/0'/i' (src/wallet/wallet.cpp CWallet::DeriveNewChildKey,
0.13+ HD wallets). Vectors: the BIP's published TV1/TV2 (test_bip32.py).
"""

from __future__ import annotations

import hashlib
import hmac
import struct
from typing import Optional

from ..crypto import secp256k1 as secp
from ..crypto.base58 import b58check_decode, b58check_encode
from ..crypto.hashes import hash160

HARDENED = 0x80000000

# mainnet version bytes (testnet's tprv/tpub differ; the extended-key
# encoding is an interchange format, so we keep mainnet like the dumps)
XPRV_VERSION = bytes.fromhex("0488ADE4")
XPUB_VERSION = bytes.fromhex("0488B21E")


class ExtKey:
    """CExtKey / CExtPubKey in one: private when `secret` is set."""

    __slots__ = ("depth", "parent_fingerprint", "child_number", "chain_code",
                 "secret", "point")

    def __init__(self, depth: int, parent_fingerprint: bytes,
                 child_number: int, chain_code: bytes,
                 secret: Optional[int] = None, point=None):
        self.depth = depth
        self.parent_fingerprint = parent_fingerprint
        self.child_number = child_number
        self.chain_code = chain_code
        self.secret = secret
        self.point = point if point is not None else (
            secp.point_mul(secret, secp.G) if secret else None
        )

    # -- construction ----------------------------------------------------

    @classmethod
    def from_seed(cls, seed: bytes) -> "ExtKey":
        """CExtKey::SetMaster — HMAC-SHA512("Bitcoin seed", seed)."""
        digest = hmac.new(b"Bitcoin seed", seed, hashlib.sha512).digest()
        secret = int.from_bytes(digest[:32], "big")
        if not (1 <= secret < secp.N):
            raise ValueError("invalid seed (master key out of range)")
        return cls(0, b"\x00" * 4, 0, digest[32:], secret=secret)

    @property
    def is_private(self) -> bool:
        return self.secret is not None

    def pubkey_bytes(self) -> bytes:
        return secp.pubkey_serialize(self.point, compressed=True)

    def fingerprint(self) -> bytes:
        return hash160(self.pubkey_bytes())[:4]

    def neuter(self) -> "ExtKey":
        """CExtKey::Neuter — the corresponding extended public key."""
        return ExtKey(self.depth, self.parent_fingerprint, self.child_number,
                      self.chain_code, secret=None, point=self.point)

    # -- derivation ------------------------------------------------------

    def derive(self, i: int) -> "ExtKey":
        """CKDpriv / CKDpub (CExtKey::Derive, CExtPubKey::Derive)."""
        hardened = bool(i & HARDENED)
        if hardened:
            if not self.is_private:
                raise ValueError("hardened derivation from a public key")
            data = b"\x00" + self.secret.to_bytes(32, "big")
        else:
            data = self.pubkey_bytes()
        digest = hmac.new(self.chain_code,
                          data + struct.pack(">I", i), hashlib.sha512).digest()
        tweak = int.from_bytes(digest[:32], "big")
        if tweak >= secp.N:
            raise ValueError("derivation tweak out of range (try next index)")
        if self.is_private:
            child_secret = (self.secret + tweak) % secp.N
            if child_secret == 0:
                raise ValueError("zero child key (try next index)")
            return ExtKey(self.depth + 1, self.fingerprint(), i,
                          digest[32:], secret=child_secret)
        child_point = secp.point_add(secp.point_mul(tweak, secp.G), self.point)
        if child_point is None:
            raise ValueError("infinity child key (try next index)")
        return ExtKey(self.depth + 1, self.fingerprint(), i,
                      digest[32:], secret=None, point=child_point)

    def derive_path(self, path: str) -> "ExtKey":
        """'m/0'/0'/5'' or 'm/44/0/1h' style paths."""
        node = self
        parts = path.split("/")
        if parts and parts[0] in ("m", "M", ""):
            parts = parts[1:]
        for part in parts:
            if not part:
                continue
            hardened = part[-1] in ("'", "h", "H")
            idx = int(part[:-1] if hardened else part)
            node = node.derive(idx | (HARDENED if hardened else 0))
        return node

    # -- serialization (base58check xprv/xpub) ---------------------------

    def serialize(self) -> str:
        if self.is_private:
            version = XPRV_VERSION
            keydata = b"\x00" + self.secret.to_bytes(32, "big")
        else:
            version = XPUB_VERSION
            keydata = self.pubkey_bytes()
        payload = (version + bytes([self.depth]) + self.parent_fingerprint
                   + struct.pack(">I", self.child_number)
                   + self.chain_code + keydata)
        return b58check_encode(payload)

    @classmethod
    def parse(cls, encoded: str) -> Optional["ExtKey"]:
        payload = b58check_decode(encoded)
        if payload is None or len(payload) != 78:
            return None
        version, rest = payload[:4], payload[4:]
        depth = rest[0]
        fingerprint = rest[1:5]
        (child_number,) = struct.unpack(">I", rest[5:9])
        chain_code = rest[9:41]
        keydata = rest[41:74]
        if version == XPRV_VERSION:
            if keydata[0] != 0:
                return None
            secret = int.from_bytes(keydata[1:], "big")
            if not (1 <= secret < secp.N):
                return None
            return cls(depth, fingerprint, child_number, chain_code,
                       secret=secret)
        if version == XPUB_VERSION:
            point = secp.pubkey_parse(keydata)
            if point is None:
                return None
            return cls(depth, fingerprint, child_number, chain_code,
                       secret=None, point=point)
        return None
