"""Wallet encryption — src/wallet/crypter.{h,cpp} (CCrypter, CMasterKey,
CCryptoKeyStore semantics).

Scheme (exactly the reference's):
  - A random 32-byte *master key* encrypts every private key.
  - The master key itself is stored encrypted under a key derived from the
    user passphrase: SHA-512(passphrase || salt) iterated `rounds` times
    (BytesToKeySHA512AES — key = digest[0:32], iv = digest[32:48]).
  - Each secret is AES-256-CBC encrypted under (master key, iv) where
    iv = sha256d(pubkey)[0:16] — binding ciphertext to its key pair.
  - Unlock = decrypt master key with the passphrase-derived key and check a
    known pubkey round-trips; wrong passphrase -> padding/verify failure.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field

from ..crypto.aes import aes256_cbc_decrypt, aes256_cbc_encrypt
from ..crypto.hashes import sha256d

DEFAULT_ROUNDS = 25_000  # the reference calibrates to ~100ms; fixed here


def bytes_to_key_sha512(passphrase: bytes, salt: bytes,
                        rounds: int) -> tuple[bytes, bytes]:
    """BytesToKeySHA512AES: iterated SHA-512 KDF -> (32-byte key, 16-byte iv)."""
    assert rounds >= 1
    d = hashlib.sha512(passphrase + salt).digest()
    for _ in range(rounds - 1):
        d = hashlib.sha512(d).digest()
    return d[:32], d[32:48]


@dataclass
class MasterKey:
    """CMasterKey: the encrypted master key record (wallet.dat mkey)."""

    encrypted_key: bytes
    salt: bytes
    rounds: int = DEFAULT_ROUNDS

    def to_dict(self) -> dict:
        return {"encrypted_key": self.encrypted_key.hex(),
                "salt": self.salt.hex(), "rounds": self.rounds}

    @classmethod
    def from_dict(cls, d: dict) -> "MasterKey":
        return cls(bytes.fromhex(d["encrypted_key"]),
                   bytes.fromhex(d["salt"]), d["rounds"])


def new_master_key(passphrase: str,
                   rounds: int = DEFAULT_ROUNDS) -> tuple[MasterKey, bytes]:
    """EncryptKeys setup: generate a random master key, seal it under the
    passphrase. Returns (record, plaintext master key)."""
    master = os.urandom(32)
    salt = os.urandom(8)
    key, iv = bytes_to_key_sha512(passphrase.encode(), salt, rounds)
    return MasterKey(aes256_cbc_encrypt(key, iv, master), salt, rounds), master


def unseal_master_key(mk: MasterKey, passphrase: str) -> bytes | None:
    """Decrypt the master key; None on wrong passphrase (bad padding)."""
    key, iv = bytes_to_key_sha512(passphrase.encode(), mk.salt, mk.rounds)
    try:
        out = aes256_cbc_decrypt(key, iv, mk.encrypted_key)
    except ValueError:
        return None
    return out if len(out) == 32 else None


def secret_iv(pubkey: bytes) -> bytes:
    """Per-key iv: sha256d(pubkey)[0:16] (EncryptSecret's chIV)."""
    return sha256d(pubkey)[:16]


def encrypt_secret(master: bytes, secret32: bytes, pubkey: bytes) -> bytes:
    assert len(secret32) == 32
    return aes256_cbc_encrypt(master, secret_iv(pubkey), secret32)


def decrypt_secret(master: bytes, ciphertext: bytes,
                   pubkey: bytes) -> bytes | None:
    try:
        out = aes256_cbc_decrypt(master, secret_iv(pubkey), ciphertext)
    except ValueError:
        return None
    return out if len(out) == 32 else None
