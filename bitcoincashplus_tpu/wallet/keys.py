"""Keys and addresses.

Reference: src/key.{h,cpp} (CKey), src/pubkey.h (CPubKey),
src/base58.cpp (CBitcoinAddress, CBitcoinSecret / WIF).
"""

from __future__ import annotations

import os
from typing import Optional

from ..consensus.params import ChainParams
from ..crypto import secp256k1 as secp
from ..crypto.base58 import b58check_decode, b58check_encode
from ..crypto.hashes import hash160
from ..script.script import is_p2sh, p2pkh_script, p2sh_script


class CKey:
    """A private key + derived pubkey (src/key.h CKey)."""

    __slots__ = ("secret", "compressed", "pubkey")

    def __init__(self, secret: int, compressed: bool = True):
        if not (1 <= secret < secp.N):
            raise ValueError("secret out of range")
        self.secret = secret
        self.compressed = compressed
        self.pubkey = secp.privkey_to_pubkey(secret, compressed)

    @classmethod
    def generate(cls, compressed: bool = True) -> "CKey":
        """MakeNewKey — rejection-sample 32 random bytes (src/key.cpp)."""
        while True:
            candidate = int.from_bytes(os.urandom(32), "big")
            if 1 <= candidate < secp.N:
                return cls(candidate, compressed)

    @classmethod
    def from_wif(cls, wif: str, params: ChainParams) -> Optional["CKey"]:
        """CBitcoinSecret::SetString."""
        payload = b58check_decode(wif)
        if not payload or payload[0] != params.secret_key_prefix:
            return None
        body = payload[1:]
        if len(body) == 33 and body[-1] == 0x01:
            return cls(int.from_bytes(body[:32], "big"), compressed=True)
        if len(body) == 32:
            return cls(int.from_bytes(body, "big"), compressed=False)
        return None

    def to_wif(self, params: ChainParams) -> str:
        """CBitcoinSecret::ToString."""
        body = self.secret.to_bytes(32, "big")
        if self.compressed:
            body += b"\x01"
        return b58check_encode(bytes([params.secret_key_prefix]) + body)

    @property
    def pubkey_hash(self) -> bytes:
        return hash160(self.pubkey)

    def p2pkh_address(self, params: ChainParams) -> str:
        return b58check_encode(
            bytes([params.pubkey_addr_prefix]) + self.pubkey_hash
        )

    def p2pkh_script(self) -> bytes:
        return p2pkh_script(self.pubkey_hash)

    def sign(self, msg_hash32: bytes) -> bytes:
        """DER-encoded signature WITHOUT hashtype byte (CKey::Sign)."""
        e = int.from_bytes(msg_hash32, "big")
        from .. import native

        if native.available():
            # bit-identical to the oracle signer (same RFC6979 nonce),
            # ~100x faster — differential-tested in test_native.py
            r, s = native.ecdsa_sign(self.secret, e)
        else:
            r, s = secp.ecdsa_sign(self.secret, e)
        return secp.sig_der_encode(r, s)


def address_to_script(addr: str, params: ChainParams) -> Optional[bytes]:
    """CBitcoinAddress → scriptPubKey (DecodeDestination + GetScriptForDestination)."""
    payload = b58check_decode(addr)
    if payload is None or len(payload) != 21:
        return None
    version, h = payload[0], payload[1:]
    if version == params.pubkey_addr_prefix:
        return p2pkh_script(h)
    if version == params.script_addr_prefix:
        return p2sh_script(h)
    return None


def script_to_address(script_pubkey: bytes, params: ChainParams) -> Optional[str]:
    """scriptPubKey → address (ExtractDestination + EncodeDestination)."""
    if (
        len(script_pubkey) == 25
        and script_pubkey[:3] == bytes([0x76, 0xA9, 20])
        and script_pubkey[23:] == bytes([0x88, 0xAC])
    ):
        return b58check_encode(
            bytes([params.pubkey_addr_prefix]) + script_pubkey[3:23]
        )
    if is_p2sh(script_pubkey):
        return b58check_encode(
            bytes([params.script_addr_prefix]) + script_pubkey[2:22]
        )
    return None
