"""Wallet — key store + owned-coin tracking + spend builder + encryption.

Reference: src/wallet/wallet.cpp (CWallet::AddToWallet via the
BlockConnected signal, CWallet::CreateTransaction, AvailableCoins,
SelectCoins/ApproximateBestSubset coin selection), src/wallet/crypter.cpp
(CCryptoKeyStore: master-key encryption, Lock/Unlock). Simplified: keypool
is generate-on-demand, storage is a JSON wallet file in the datadir
(wallet.dat's role without BDB).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from ..consensus.params import ChainParams
from ..consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from ..script.script import classify_script, get_script_ops
from ..script.sighash import SIGHASH_ALL
from .crypter import (
    MasterKey,
    decrypt_secret,
    encrypt_secret,
    new_master_key,
    unseal_master_key,
)
from .keys import CKey, address_to_script
from .signing import sign_transaction


MIN_CHANGE = 1_000_000  # CENT — the reference's clean-change threshold


def _approximate_best_subset(coins, total_lower, target, rng,
                             iterations=1000):
    """ApproximateBestSubset (src/wallet/wallet.cpp): stochastic subset
    search for the sum closest to (>=) target. coins value-descending;
    returns (inclusion flags, best sum)."""
    best_set = [True] * len(coins)
    best_value = total_lower
    for _ in range(iterations):
        included = [False] * len(coins)
        total = 0
        reached = False
        for n_pass in range(2):
            for i, c in enumerate(coins):
                # pass 1: random walk; pass 2: offer everything not yet in
                want = rng.random() < 0.5 if n_pass == 0 else not included[i]
                if want and not included[i]:
                    total += c.txout.value
                    included[i] = True
                    if total >= target:
                        reached = True
                        if total < best_value:
                            best_value = total
                            best_set = included.copy()
                        total -= c.txout.value
                        included[i] = False
        if reached and best_value == target:
            break
    return best_set, best_value


class WalletError(Exception):
    pass


class WalletCoin:
    __slots__ = ("outpoint", "txout", "height", "is_coinbase")

    def __init__(self, outpoint: COutPoint, txout: CTxOut, height: int,
                 is_coinbase: bool):
        self.outpoint = outpoint
        self.txout = txout
        self.height = height
        self.is_coinbase = is_coinbase


class Wallet:
    """In-memory wallet; persistence via export_keys/import_keys (WIF)."""

    def __init__(self, params: ChainParams, path: Optional[str] = None):
        self.params = params
        self.path = path
        self.keys_by_pkh: dict[bytes, CKey] = {}
        self.keys_by_pubkey: dict[bytes, CKey] = {}
        self.coins: dict[COutPoint, WalletCoin] = {}
        self.spent: set[COutPoint] = set()
        # lockunspent: outpoints excluded from coin selection (setLockedCoins)
        self.locked_coins: set[COutPoint] = set()
        # addmultisigaddress/importaddress watch-only scripts (CScript set)
        self.watched_scripts: set[bytes] = set()
        # legacy accounts API (mapAddressBook labels + `move` deltas)
        self.labels: dict[str, str] = {}  # address -> account name
        self.account_moves: dict[str, int] = {}  # account -> moved satoshis
        # getaccountaddress's stable per-account receiving address
        self.account_addresses: dict[str, str] = {}
        # CCryptoKeyStore state: pubkey -> (ciphertext, compressed). The
        # pkh index survives Lock so IsMine keeps answering while locked.
        self.master_key_record: Optional[MasterKey] = None
        self.encrypted_keys: dict[bytes, tuple[bytes, bool]] = {}
        self._master: Optional[bytes] = None
        self._pkh_index: dict[bytes, bytes] = {}  # pkh -> pubkey
        self.unlocked_until: float = 0.0
        # mapWallet analogue: txid -> {height, received, sent, is_coinbase}
        # insertion-ordered (dict) = wallet tx history for listtransactions
        self.tx_log: dict[bytes, dict] = {}
        # HD chain (CHDChain, 0.13+ wallets): new keys derive from the
        # seed at m/0'/0'/i' (DeriveNewChildKey). None = legacy random
        # keys (e.g. a pre-HD wallet file).
        self.hd_seed: Optional[bytes] = None
        self.encrypted_hd_seed: Optional[bytes] = None
        self.hd_counter = 0
        self.key_paths: dict[bytes, str] = {}  # pubkey -> hdkeypath

    # -- encryption (CCryptoKeyStore) --

    @property
    def is_crypted(self) -> bool:
        return self.master_key_record is not None

    @property
    def is_locked(self) -> bool:
        return self.is_crypted and self._master is None

    def encrypt(self, passphrase: str) -> None:
        """EncryptWallet: seal every key under a fresh master key, then
        Lock (the reference requires walletpassphrase afterwards)."""
        if self.is_crypted:
            raise WalletError("wallet already encrypted")
        if not passphrase:
            raise WalletError("passphrase must not be empty")
        record, master = new_master_key(passphrase)
        for pubkey, key in self.keys_by_pubkey.items():
            ct = encrypt_secret(master, key.secret.to_bytes(32, "big"), pubkey)
            self.encrypted_keys[pubkey] = (ct, key.compressed)
        if self.hd_seed is not None:
            self.encrypted_hd_seed = encrypt_secret(
                master, self.hd_seed, self._HD_SEED_TAG)
            self.hd_seed = None
        self.master_key_record = record
        self.lock()
        self.save()

    def lock(self) -> None:
        if not self.is_crypted:
            raise WalletError("wallet is not encrypted")
        self._master = None
        self.unlocked_until = 0.0
        self.keys_by_pkh.clear()
        self.keys_by_pubkey.clear()
        self.hd_seed = None  # plaintext seed never survives a Lock

    def unlock(self, passphrase: str, timeout: float = 0) -> bool:
        """Unlock: False on wrong passphrase. timeout 0 = until lock()."""
        if not self.is_crypted:
            raise WalletError("wallet is not encrypted")
        master = unseal_master_key(self.master_key_record, passphrase)
        if master is None:
            return False
        restored = []
        for pubkey, (ct, compressed) in self.encrypted_keys.items():
            sec = decrypt_secret(master, ct, pubkey)
            if sec is None:
                return False
            key = CKey(int.from_bytes(sec, "big"), compressed)
            if key.pubkey != pubkey:  # integrity check (crypter.cpp Unlock)
                return False
            restored.append(key)
        for key in restored:
            self.keys_by_pkh[key.pubkey_hash] = key
            self.keys_by_pubkey[key.pubkey] = key
        if self.encrypted_hd_seed is not None:
            seed = decrypt_secret(master, self.encrypted_hd_seed,
                                  self._HD_SEED_TAG)
            if seed is None:
                return False
            self.hd_seed = seed
        self._master = master
        self.unlocked_until = time.time() + timeout if timeout else 0.0
        return True

    def maybe_relock(self) -> None:
        """nWalletUnlockTime expiry (rpcwallet.cpp LockWallet timer)."""
        if (self.is_crypted and self._master is not None
                and self.unlocked_until and time.time() > self.unlocked_until):
            self.lock()

    def change_passphrase(self, old: str, new: str) -> bool:
        if not self.is_crypted:
            raise WalletError("wallet is not encrypted")
        master = unseal_master_key(self.master_key_record, old)
        if master is None:
            return False
        record, fresh = new_master_key(new)
        # re-seal every secret under the new master key
        new_encrypted = {}
        for pubkey, (ct, compressed) in self.encrypted_keys.items():
            sec = decrypt_secret(master, ct, pubkey)
            if sec is None:
                return False
            new_encrypted[pubkey] = (
                encrypt_secret(fresh, sec, pubkey), compressed
            )
        if self.encrypted_hd_seed is not None:
            seed = decrypt_secret(master, self.encrypted_hd_seed,
                                  self._HD_SEED_TAG)
            if seed is None:
                return False
            self.encrypted_hd_seed = encrypt_secret(fresh, seed,
                                                    self._HD_SEED_TAG)
        self.encrypted_keys = new_encrypted
        self.master_key_record = record
        if self._master is not None:
            self._master = fresh
        self.save()
        return True

    # -- keys --

    def add_key(self, key: CKey, persist: bool = True) -> None:
        if self.is_locked:
            raise WalletError("cannot add keys to a locked wallet")
        self.keys_by_pkh[key.pubkey_hash] = key
        self.keys_by_pubkey[key.pubkey] = key
        self._pkh_index[key.pubkey_hash] = key.pubkey
        if self.is_crypted:
            self.encrypted_keys[key.pubkey] = (
                encrypt_secret(self._master, key.secret.to_bytes(32, "big"),
                               key.pubkey),
                key.compressed,
            )
        if persist:
            self.save()

    # IV tag for sealing the HD seed (it has no pubkey of its own)
    _HD_SEED_TAG = b"\x04hdseed" * 4

    def derive_new_key(self) -> CKey:
        """CWallet::DeriveNewChildKey — next key at m/0'/0'/i' from the HD
        seed; falls back to a random key for legacy (pre-HD) wallets."""
        if self.is_locked:
            raise WalletError("cannot derive keys from a locked wallet")
        if self.hd_seed is None:
            if self.is_crypted or self.keys_by_pubkey or self._pkh_index:
                # legacy wallet (had keys before HD existed): stay random
                return CKey.generate()
            self.hd_seed = os.urandom(32)
        from .bip32 import ExtKey

        master = ExtKey.from_seed(self.hd_seed)
        account = master.derive_path("m/0'/0'")
        while True:
            i = self.hd_counter
            self.hd_counter += 1
            try:
                node = account.derive(i | 0x80000000)
            except ValueError:
                continue  # invalid index (~2^-127): skip, like the reference
            key = CKey(node.secret)
            self.key_paths[key.pubkey] = f"m/0'/0'/{i}'"
            return key

    def get_new_address(self, account: str = "") -> str:
        key = self.derive_new_key()
        self.add_key(key)
        addr = key.p2pkh_address(self.params)
        if account:
            self.labels[addr] = account
            self.save()
        return addr

    # -- persistence (wallet.dat role) --

    def save(self) -> None:
        if not self.path:
            return
        if self.is_crypted:
            payload = {
                "version": 2,
                "master_key": self.master_key_record.to_dict(),
                "encrypted_keys": [
                    {"pubkey": pk.hex(), "ct": ct.hex(), "compressed": comp}
                    for pk, (ct, comp) in self.encrypted_keys.items()
                ],
            }
            if self.encrypted_hd_seed is not None:
                payload["hd_seed_ct"] = self.encrypted_hd_seed.hex()
        else:
            payload = {
                "version": 2,
                "keys": [
                    {"wif": k.to_wif(self.params)}
                    for k in self.keys_by_pubkey.values()
                ],
            }
            if self.hd_seed is not None:
                payload["hd_seed"] = self.hd_seed.hex()
        payload["hd_counter"] = self.hd_counter
        payload["key_paths"] = {
            pk.hex(): path for pk, path in self.key_paths.items()
        }
        if self.watched_scripts:
            payload["watched_scripts"] = [
                s.hex() for s in self.watched_scripts
            ]
        if self.labels:
            payload["labels"] = dict(self.labels)
        if self.account_moves:
            payload["account_moves"] = dict(self.account_moves)
        if self.account_addresses:
            payload["account_addresses"] = dict(self.account_addresses)
        tmp = self.path + ".tmp"
        # 0600: the plaintext form carries WIF keys (same treatment as the
        # RPC .cookie); encrypted form too — no reason to leak either
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)  # atomic, crash-safe

    def load(self) -> None:
        if not (self.path and os.path.exists(self.path)):
            return
        with open(self.path) as f:
            payload = json.load(f)
        if "master_key" in payload:
            self.master_key_record = MasterKey.from_dict(payload["master_key"])
            for rec in payload["encrypted_keys"]:
                pubkey = bytes.fromhex(rec["pubkey"])
                self.encrypted_keys[pubkey] = (
                    bytes.fromhex(rec["ct"]), rec["compressed"]
                )
                from ..crypto.hashes import hash160

                self._pkh_index[hash160(pubkey)] = pubkey
        else:
            for rec in payload.get("keys", []):
                key = CKey.from_wif(rec["wif"], self.params)
                if key is not None:
                    self.add_key(key, persist=False)
            if "hd_seed" in payload:
                self.hd_seed = bytes.fromhex(payload["hd_seed"])
        if "hd_seed_ct" in payload:
            self.encrypted_hd_seed = bytes.fromhex(payload["hd_seed_ct"])
        self.hd_counter = payload.get("hd_counter", 0)
        self.key_paths = {
            bytes.fromhex(pk): path
            for pk, path in payload.get("key_paths", {}).items()
        }
        self.watched_scripts = {
            bytes.fromhex(s) for s in payload.get("watched_scripts", [])
        }
        self.labels = dict(payload.get("labels", {}))
        self.account_moves = dict(payload.get("account_moves", {}))
        self.account_addresses = dict(payload.get("account_addresses", {}))

    def key_for_id(self, ident: bytes) -> Optional[CKey]:
        """Solver callback: 20-byte pubkey hash or raw pubkey."""
        if len(ident) == 20:
            return self.keys_by_pkh.get(ident)
        return self.keys_by_pubkey.get(ident)

    def _is_mine(self, script_pubkey: bytes) -> bool:
        """IsMine (src/script/ismine.cpp) for the templates we hold keys to.
        Answers from the lock-surviving indexes so a locked wallet still
        tracks its coins (CCryptoKeyStore::HaveKey semantics)."""
        if script_pubkey in self.watched_scripts:
            return True
        kind = classify_script(script_pubkey)
        try:
            if kind == "pubkeyhash":
                ops = list(get_script_ops(script_pubkey))
                return (ops[2][1] in self.keys_by_pkh
                        or ops[2][1] in self._pkh_index)
            if kind == "pubkey":
                ops = list(get_script_ops(script_pubkey))
                return (ops[0][1] in self.keys_by_pubkey
                        or ops[0][1] in self.encrypted_keys)
        except Exception:
            return False
        return False

    # -- chain notifications (validationinterface analogues) --

    def block_connected(self, block, idx) -> None:
        for tx in block.vtx:
            self.add_tx_if_mine(tx, idx.height, tx.is_coinbase())

    def block_disconnected(self, block, idx) -> None:
        for tx in block.vtx:
            txid = tx.txid
            for i in range(len(tx.vout)):
                self.coins.pop(COutPoint(txid, i), None)
            for txin in tx.vin:
                self.spent.discard(txin.prevout)
            entry = self.tx_log.get(txid)
            if entry is not None:
                if tx.is_coinbase():
                    self.tx_log.pop(txid, None)  # orphaned generate
                else:
                    entry["height"] = -1  # back to unconfirmed

    def add_tx_if_mine(self, tx: CTransaction, height: int,
                       is_coinbase: bool) -> None:
        sent = 0
        for txin in tx.vin:
            coin = self.coins.get(txin.prevout)
            if coin is not None:
                self.spent.add(txin.prevout)
                sent += coin.txout.value
        txid = tx.txid
        received = 0
        for i, out in enumerate(tx.vout):
            if self._is_mine(out.script_pubkey):
                op = COutPoint(txid, i)
                self.coins[op] = WalletCoin(op, out, height, is_coinbase)
                received += out.value
        if sent or received:
            # AddToWallet: record/refresh the history entry (a mempool tx
            # re-entering via a block keeps one entry, height updated)
            entry = {
                "height": height,
                "received": received,
                "sent": sent,
                "is_coinbase": is_coinbase,
            }
            if height < 0:
                # keep the raw tx while unconfirmed (mapWallet holds the
                # CWalletTx); needed by abandontransaction
                entry["tx"] = tx
            self.tx_log[txid] = entry

    def abandon_transaction(self, txid: bytes) -> None:
        """AbandonTransaction (wallet.cpp): free the inputs of an
        unconfirmed wallet tx and forget its outputs so the coins become
        spendable again. Caller ensures the tx is not in mempool/chain."""
        entry = self.tx_log.get(txid)
        if entry is None or entry["height"] >= 0 or "tx" not in entry:
            raise WalletError("transaction is confirmed or not in wallet")
        tx = entry["tx"]
        for txin in tx.vin:
            self.spent.discard(txin.prevout)
        for i in range(len(tx.vout)):
            self.coins.pop(COutPoint(txid, i), None)
        entry["abandoned"] = True

    # -- balance / spend --

    def available_coins(self, tip_height: int,
                        include_watch_only: bool = False) -> list[WalletCoin]:
        """AvailableCoins: unspent, mature, spendable (watch-only coins —
        e.g. addmultisigaddress scripts — only with include_watch_only,
        mirroring the reference's fIncludeWatching split)."""
        maturity = self.params.consensus.coinbase_maturity
        out = []
        for op, coin in self.coins.items():
            if op in self.spent or op in self.locked_coins:
                continue
            if coin.is_coinbase and tip_height - coin.height + 1 < maturity:
                continue
            if not include_watch_only and not self.can_sign(
                    coin.txout.script_pubkey):
                continue
            out.append(coin)
        return out

    def balance(self, tip_height: int) -> int:
        """getbalance: spendable funds only (watch-only excluded)."""
        return sum(c.txout.value for c in self.available_coins(tip_height))

    def can_sign(self, script_pubkey: bytes) -> bool:
        """Do we hold the key for this script (vs merely watching it)?"""
        kind = classify_script(script_pubkey)
        try:
            if kind == "pubkeyhash":
                pkh = list(get_script_ops(script_pubkey))[2][1]
                return pkh in self.keys_by_pkh or pkh in self._pkh_index
            if kind == "pubkey":
                pk = list(get_script_ops(script_pubkey))[0][1]
                return pk in self.keys_by_pubkey or pk in self.encrypted_keys
        except Exception:
            return False
        return False

    def select_coins(self, coins: list, target: int) -> list:
        """SelectCoins / ApproximateBestSubset (src/wallet/wallet.cpp):

        1. a coin of exactly ``target`` wins outright;
        2. if the coins smaller than target + MIN_CHANGE sum to exactly
           target, use them all;
        3. otherwise a stochastic knapsack over those smaller coins looks
           for the subset sum closest to (>=) target, and the smallest
           single larger coin beats the subset when the subset can't get
           within MIN_CHANGE (the reference's tie-break).

        Replaces round-1..4's largest-first (which overshot small spends
        with one huge coin and minted maximal change — VERDICT r4 item 10).
        Deterministic per (coin set, target): seeded RNG, so tests and
        replays reproduce."""
        import random as _random

        lower = []  # coins < target + MIN_CHANGE, value-descending
        lowest_larger = None
        for c in sorted(coins, key=lambda c: c.txout.value, reverse=True):
            v = c.txout.value
            if v == target:
                return [c]
            if v < target + MIN_CHANGE:
                lower.append(c)
            elif lowest_larger is None or v < lowest_larger.txout.value:
                lowest_larger = c
        total_lower = sum(c.txout.value for c in lower)
        if total_lower == target:
            return lower
        if total_lower < target:
            if lowest_larger is None:
                raise ValueError(
                    f"insufficient funds: {total_lower} < {target}")
            return [lowest_larger]

        rng = _random.Random(0x5E1EC7 ^ target ^ len(coins))
        best_set, best_value = _approximate_best_subset(
            lower, total_lower, target, rng)
        if best_value != target and total_lower >= target + MIN_CHANGE:
            alt_set, alt_value = _approximate_best_subset(
                lower, total_lower, target + MIN_CHANGE, rng)
            if alt_value != best_value and alt_value >= target:
                best_set, best_value = alt_set, alt_value
        # the single larger coin wins when the subset is not clean change
        # and the coin wastes less (wallet.cpp's comparison)
        if lowest_larger is not None and (
            (best_value != target and best_value < target + MIN_CHANGE)
            or lowest_larger.txout.value <= best_value
        ):
            return [lowest_larger]
        return [c for c, used in zip(lower, best_set) if used]

    def create_transaction(
        self,
        address: str,
        amount: int,
        tip_height: int,
        fee: int = 1000,
        enable_forkid: bool = False,
        fee_rate: Optional[int] = None,
    ) -> CTransaction:
        script_pubkey = address_to_script(address, self.params)
        if script_pubkey is None:
            raise ValueError(f"bad address {address}")
        return self.create_transaction_multi(
            [(script_pubkey, amount)], tip_height, fee=fee,
            enable_forkid=enable_forkid, fee_rate=fee_rate,
        )

    def create_transaction_multi(
        self,
        outputs: list[tuple[bytes, int]],
        tip_height: int,
        fee: int = 1000,
        enable_forkid: bool = False,
        fee_rate: Optional[int] = None,
    ) -> CTransaction:
        """CWallet::CreateTransaction: select coins (largest-first), build,
        sign, with change back to a fresh key.

        ``fee`` is the flat floor; with ``fee_rate`` (sat/kB) the fee
        scales with the ESTIMATED size like the reference's selection loop
        — a wallet full of small UTXOs needs hundreds of inputs, and a
        flat 1000-sat fee on a 40 kB transaction would be rejected by
        every relay policy on the network (and by our own ATMP)."""
        if self.is_locked:
            raise WalletError(
                "wallet is locked; unlock with walletpassphrase first"
            )
        amount = sum(v for _s, v in outputs)
        coins = self.available_coins(tip_height)
        fee_used = fee
        while True:
            selected = self.select_coins(coins, amount + fee_used)
            total = sum(c.txout.value for c in selected)
            if fee_rate is None:
                break
            # ~148 B per P2PKH input, ~34 B per output (+1 for change)
            size_est = 10 + len(selected) * 148 + (len(outputs) + 1) * 34
            required = max(fee, -(-size_est * fee_rate // 1000))
            if amount + required <= total:
                fee_used = required
                break
            fee_used = required  # re-select at the larger fee target

        vout = [CTxOut(v, s) for s, v in outputs]
        change = total - amount - fee_used
        if change > 546:  # dust threshold (policy)
            change_key = self.derive_new_key()
            self.add_key(change_key)
            vout.append(CTxOut(change, change_key.p2pkh_script()))

        unsigned = CTransaction(
            vin=tuple(CTxIn(c.outpoint) for c in selected),
            vout=tuple(vout),
        )
        signed = sign_transaction(
            unsigned,
            [(c.txout.script_pubkey, c.txout.value) for c in selected],
            self.key_for_id,
            SIGHASH_ALL,
            enable_forkid=enable_forkid,
        )
        for c in selected:
            self.spent.add(c.outpoint)
        self.add_tx_if_mine(signed, -1, False)
        return signed
