"""Wallet — key store + owned-coin tracking + spend builder.

Reference: src/wallet/wallet.cpp (CWallet::AddToWallet via the
BlockConnected signal, CWallet::CreateTransaction, AvailableCoins,
coin selection). Simplified: keypool is generate-on-demand, coin
selection is largest-first (the reference's knapsack is a policy
optimization, not consensus), storage is the node's kvstore.
"""

from __future__ import annotations

from typing import Optional

from ..consensus.params import ChainParams
from ..consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from ..script.script import classify_script, get_script_ops
from ..script.sighash import SIGHASH_ALL
from .keys import CKey, address_to_script
from .signing import sign_transaction


class WalletCoin:
    __slots__ = ("outpoint", "txout", "height", "is_coinbase")

    def __init__(self, outpoint: COutPoint, txout: CTxOut, height: int,
                 is_coinbase: bool):
        self.outpoint = outpoint
        self.txout = txout
        self.height = height
        self.is_coinbase = is_coinbase


class Wallet:
    """In-memory wallet; persistence via export_keys/import_keys (WIF)."""

    def __init__(self, params: ChainParams):
        self.params = params
        self.keys_by_pkh: dict[bytes, CKey] = {}
        self.keys_by_pubkey: dict[bytes, CKey] = {}
        self.coins: dict[COutPoint, WalletCoin] = {}
        self.spent: set[COutPoint] = set()

    # -- keys --

    def add_key(self, key: CKey) -> None:
        self.keys_by_pkh[key.pubkey_hash] = key
        self.keys_by_pubkey[key.pubkey] = key

    def get_new_address(self) -> str:
        key = CKey.generate()
        self.add_key(key)
        return key.p2pkh_address(self.params)

    def key_for_id(self, ident: bytes) -> Optional[CKey]:
        """Solver callback: 20-byte pubkey hash or raw pubkey."""
        if len(ident) == 20:
            return self.keys_by_pkh.get(ident)
        return self.keys_by_pubkey.get(ident)

    def _is_mine(self, script_pubkey: bytes) -> bool:
        """IsMine (src/script/ismine.cpp) for the templates we hold keys to."""
        kind = classify_script(script_pubkey)
        try:
            if kind == "pubkeyhash":
                ops = list(get_script_ops(script_pubkey))
                return ops[2][1] in self.keys_by_pkh
            if kind == "pubkey":
                ops = list(get_script_ops(script_pubkey))
                return ops[0][1] in self.keys_by_pubkey
        except Exception:
            return False
        return False

    # -- chain notifications (validationinterface analogues) --

    def block_connected(self, block, idx) -> None:
        for tx in block.vtx:
            self.add_tx_if_mine(tx, idx.height, tx.is_coinbase())

    def block_disconnected(self, block, idx) -> None:
        for tx in block.vtx:
            txid = tx.txid
            for i in range(len(tx.vout)):
                self.coins.pop(COutPoint(txid, i), None)
            for txin in tx.vin:
                self.spent.discard(txin.prevout)

    def add_tx_if_mine(self, tx: CTransaction, height: int,
                       is_coinbase: bool) -> None:
        for txin in tx.vin:
            if txin.prevout in self.coins:
                self.spent.add(txin.prevout)
        txid = tx.txid
        for i, out in enumerate(tx.vout):
            if self._is_mine(out.script_pubkey):
                op = COutPoint(txid, i)
                self.coins[op] = WalletCoin(op, out, height, is_coinbase)

    # -- balance / spend --

    def available_coins(self, tip_height: int) -> list[WalletCoin]:
        """AvailableCoins: unspent, mature."""
        maturity = self.params.consensus.coinbase_maturity
        out = []
        for op, coin in self.coins.items():
            if op in self.spent:
                continue
            if coin.is_coinbase and tip_height - coin.height + 1 < maturity:
                continue
            out.append(coin)
        return out

    def balance(self, tip_height: int) -> int:
        return sum(c.txout.value for c in self.available_coins(tip_height))

    def create_transaction(
        self,
        address: str,
        amount: int,
        tip_height: int,
        fee: int = 1000,
        enable_forkid: bool = False,
    ) -> CTransaction:
        """CWallet::CreateTransaction: select coins (largest-first), build,
        sign, with change back to a fresh key."""
        script_pubkey = address_to_script(address, self.params)
        if script_pubkey is None:
            raise ValueError(f"bad address {address}")
        coins = sorted(
            self.available_coins(tip_height),
            key=lambda c: c.txout.value, reverse=True,
        )
        selected, total = [], 0
        for coin in coins:
            selected.append(coin)
            total += coin.txout.value
            if total >= amount + fee:
                break
        if total < amount + fee:
            raise ValueError(f"insufficient funds: {total} < {amount + fee}")

        vout = [CTxOut(amount, script_pubkey)]
        change = total - amount - fee
        if change > 546:  # dust threshold (policy)
            change_key = CKey.generate()
            self.add_key(change_key)
            vout.append(CTxOut(change, change_key.p2pkh_script()))

        unsigned = CTransaction(
            vin=tuple(CTxIn(c.outpoint) for c in selected),
            vout=tuple(vout),
        )
        signed = sign_transaction(
            unsigned,
            [(c.txout.script_pubkey, c.txout.value) for c in selected],
            self.key_for_id,
            SIGHASH_ALL,
            enable_forkid=enable_forkid,
        )
        for c in selected:
            self.spent.add(c.outpoint)
        self.add_tx_if_mine(signed, -1, False)
        return signed
