"""Signature cache.

Reference: src/script/sigcache.cpp:~70 (CSignatureCache) — memoizes
(sighash, pubkey, signature) triples so signatures verified at mempool
acceptance skip re-verification in ConnectBlock. Keyed identically;
consulted BEFORE building the TPU batch (SURVEY.md §3.1 sigcache row),
so steady-state block connects dispatch only never-seen signatures.

Bounded FIFO eviction via an ordered dict (the reference uses randomized
eviction / a cuckoo table; FIFO preserves the same contract — presence
implies validity — without the tuning surface)."""

from __future__ import annotations

from collections import OrderedDict


class SignatureCache:
    def __init__(self, max_entries: int = 1 << 16):
        self.max_entries = max_entries
        self._set: OrderedDict[bytes, None] = OrderedDict()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def entry_key(msg_hash: int, r: int, s: int, pubkey: tuple) -> bytes:
        return (
            msg_hash.to_bytes(32, "big")
            + r.to_bytes(32, "big")
            + s.to_bytes(32, "big")
            + pubkey[0].to_bytes(32, "big")
            + (pubkey[1] & 1).to_bytes(1, "big")
        )

    def contains(self, key: bytes) -> bool:
        if key in self._set:
            self.hits += 1
            return True
        self.misses += 1
        return False

    def add(self, key: bytes) -> None:
        self._set[key] = None
        while len(self._set) > self.max_entries:
            self._set.popitem(last=False)

    def __len__(self) -> int:
        return len(self._set)
