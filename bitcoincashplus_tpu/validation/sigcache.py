"""Signature cache.

Reference: src/script/sigcache.cpp:~70 (CSignatureCache) — memoizes
(sighash, pubkey, signature) triples so signatures verified at mempool
acceptance skip re-verification in ConnectBlock. Keyed identically;
consulted BEFORE building the TPU batch (SURVEY.md §3.1 sigcache row),
so steady-state block connects dispatch only never-seen signatures.

Bounded LRU-ish eviction via an ordered dict: a probe hit refreshes the
entry (move-to-end), eviction pops the stalest. The reference uses
randomized eviction / a cuckoo table; the LRU discipline preserves the
same contract — presence implies validity — while keeping the hot
mempool->block working set resident under IBD churn. Capped both in
entries and in estimated bytes (-maxsigcachesize), whichever binds
first; hit/miss/insert/eviction counters feed gettpuinfo.sigcache.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from ..util import lockwatch

# Estimated resident cost per entry: the 130-byte key's bytes object
# (~163 B via sys.getsizeof) plus the OrderedDict slot/link overhead.
ENTRY_COST_BYTES = 280

# Scheme tag byte appended to every entry key. Schnorr and ECDSA share
# the (sighash, r, s, pubkey) byte layout — a 64-byte Schnorr body is
# indistinguishable from a decoded DER (r, s) pair once parsed to ints —
# so without the tag a cached ECDSA TRUE would satisfy a Schnorr probe
# for the same byte material (and vice versa): presence-implies-validity
# would cross schemes. The tag makes the keyspace disjoint per algorithm.
_ALGO_TAGS = {"ecdsa": b"\x00", "schnorr": b"\x01"}


class SignatureCache:
    def __init__(self, max_entries: int = 1 << 16,
                 max_bytes: Optional[int] = None):
        self.max_entries = max_entries
        self.max_bytes = max_bytes  # None = entry cap only
        self._set: OrderedDict[bytes, None] = OrderedDict()
        # the SigService settle thread inserts verdicts concurrently with
        # accept/connect threads probing under cs_main: the compound
        # probe (membership + move_to_end) and insert (set + evict) are
        # NOT GIL-atomic — an unguarded probe could move_to_end a key the
        # settle thread's eviction just popped (KeyError out of a valid
        # block's validation). Plain Lock normally; the BCP_LOCKWATCH
        # sentinel wraps it into the lock-order graph (util/lockwatch).
        self._lock = lockwatch.watched_lock("sigcache")
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        # serving-path in-flight dedup (serving/sigservice): records that
        # missed the cache but joined an already-in-flight lane for the
        # same (sighash, r, s, pubkey) key — verified once, served twice
        self.service_dedup_hits = 0

    @staticmethod
    def entry_key(msg_hash: int, r: int, s: int, pubkey: tuple,
                  algo: str = "ecdsa") -> bytes:
        return (
            msg_hash.to_bytes(32, "big")
            + r.to_bytes(32, "big")
            + s.to_bytes(32, "big")
            + pubkey[0].to_bytes(32, "big")
            + (pubkey[1] & 1).to_bytes(1, "big")
            + _ALGO_TAGS[algo]
        )

    def note_dedup(self) -> None:
        """A SigService in-flight dedup hit (the cache itself missed, but
        the verdict was already being computed)."""
        self.service_dedup_hits += 1

    def contains(self, key: bytes) -> bool:
        with self._lock:
            if key in self._set:
                self.hits += 1
                self._set.move_to_end(key)  # LRU refresh
                return True
            self.misses += 1
            return False

    def _over_budget(self) -> bool:
        if len(self._set) > self.max_entries:
            return True
        return (self.max_bytes is not None
                and len(self._set) * ENTRY_COST_BYTES > self.max_bytes)

    def add(self, key: bytes) -> None:
        with self._lock:
            if key not in self._set:
                self.inserts += 1
            self._set[key] = None
            self._set.move_to_end(key)
            while self._set and self._over_budget():
                self._set.popitem(last=False)  # stalest first
                self.evictions += 1

    def estimated_bytes(self) -> int:
        return len(self._set) * ENTRY_COST_BYTES

    def snapshot(self) -> dict:
        """gettpuinfo.sigcache section."""
        probes = self.hits + self.misses
        return {
            "entries": len(self._set),
            "bytes": self.estimated_bytes(),
            "max_entries": self.max_entries,
            "max_bytes": self.max_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "inserts": self.inserts,
            "evictions": self.evictions,
            "service_dedup_hits": self.service_dedup_hits,
            "hit_rate": round(self.hits / probes, 4) if probes else 0.0,
        }

    def __len__(self) -> int:
        return len(self._set)
