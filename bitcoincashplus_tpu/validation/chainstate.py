"""The consensus state machine — ChainstateManager.

Reference: src/validation.cpp. Function-by-function parity (SURVEY.md §3.1):
ProcessNewBlock (:~3100), AcceptBlock (:~3000), AcceptBlockHeader,
CheckBlock, ConnectBlock (:~1700), DisconnectBlock, ActivateBestChain
(:~2500), InvalidateBlock, FlushStateToDisk (:~1900).

Differences from the reference, by design (TPU-first, SURVEY.md §1):
  - Single-threaded host orchestration (no cs_main; Python + asyncio).
  - Script/signature checks are not fanned out to a thread pool
    (CCheckQueue); they are *deferred* into per-block batch records and
    dispatched to the TPU ECDSA kernel in one shot (ops/ecdsa_batch), with
    a CPU fallback. The `script_verifier` hook owns that policy.
  - Header PoW / Merkle recomputation can run batched on-chip.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

from ..consensus.block import CBlock, CBlockHeader
from ..consensus.params import ChainParams, get_block_subsidy
from ..consensus.pow import check_proof_of_work, get_next_work_required
from ..consensus.serialize import hash_to_hex
from ..consensus.tx import COutPoint, CTransaction, money_range
from ..consensus.tx_check import TxValidationError, check_transaction, is_final_tx
from ..script.script import script_int
from ..util import devicewatch as dw
from ..util import telemetry as tm
from ..util.log import log_print
from .chain import BlockStatus, CBlockIndex, CChain
from .coins import BlockUndo, CoinsCache, CoinsView, TxUndo, add_coins

MAX_FUTURE_BLOCK_TIME = 2 * 60 * 60  # src/chain.h (MAX_FUTURE_BLOCK_TIME)

# -- telemetry (util/telemetry): the pipelined engine's per-block leg
# latencies as histograms, and scan/settle/commit spans so a -tracefile
# dump yields a MEASURED per-block overlap fraction (tools/trace_view.py)
# instead of the bench-only aggregate estimate.
_SCAN_H = tm.histogram(
    "bcp_pipeline_scan_seconds",
    "Speculative connect + host script scan per block")
_SETTLE_H = tm.histogram(
    "bcp_pipeline_settle_wait_seconds",
    "Blocking wait for a block's signature batches at settle")
_COMMIT_H = tm.histogram(
    "bcp_pipeline_commit_seconds",
    "Externalization (coins merge, undo+index write, listeners) per block")
_UNWINDS_C = tm.counter(
    "bcp_pipeline_unwind_blocks_total",
    "Speculative blocks dropped by settle-failure unwinds")
# -- speculation-tree observability (ISSUE 9): reorg accounting plus the
# per-branch shape of the settle horizon once competing tips validate
# concurrently. A "reorg" here is the externalized kind — settled blocks
# disconnected from the active chain; in-tree branch switches never
# disconnect anything and are counted as branch drops instead.
_REORGS_C = tm.counter(
    "bcp_reorgs_total",
    "Active-chain reorganizations (settled blocks disconnected)")
_REORG_DEPTH_H = tm.histogram(
    "bcp_reorg_depth",
    "Settled blocks disconnected per reorg",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 64))
_BRANCHES_G = tm.gauge(
    "bcp_spec_branches",
    "Live branches (leaves) in the speculation tree")
_LAYERS_G = tm.gauge(
    "bcp_spec_layers",
    "Speculative coin-cache layers live across all branches")
_BRANCH_DROPS_C = tm.counter(
    "bcp_spec_branch_drops_total",
    "Losing speculative branches dropped (never externalized)")


class BlockValidationError(TxValidationError):
    """Block-level reject reason (shares the reason-string contract)."""


# Type of the deferred script-verification hook: called once per block with
# (block, index, spent_coins_per_input) and must raise BlockValidationError
# on failure. Wired to the script interpreter + TPU sig batch in
# validation/scriptcheck.py. The DEFAULT is fail-closed: a
# BlockScriptVerifier is constructed unless the caller explicitly passes
# None (below-assumevalid / trusted-reindex behavior — the reference's
# fScriptChecks=false path, src/validation.cpp ConnectBlock).
ScriptVerifier = Callable[[CBlock, CBlockIndex, list], None]

_DEFAULT = object()  # sentinel: "build the real verifier"


class ChainstateManager:
    """Owns the block tree, the active chain, and the UTXO view stack."""

    def __init__(
        self,
        params: ChainParams,
        coins_base: CoinsView,
        block_store,
        script_verifier=_DEFAULT,
        get_time: Callable[[], int] = lambda: int(_time.time()),
        index_db=None,
    ):
        if script_verifier is _DEFAULT:
            from .scriptcheck import BlockScriptVerifier

            script_verifier = BlockScriptVerifier(params)
        # startup replay/rollback: a journaled coins store (CoinsDB with a
        # journal path) may hold a commit that crashed mid-flight; resolve
        # it to a whole pre- or post-batch state BEFORE anything reads the
        # best-block marker (store/chainstatedb.py commit-journal contract)
        recover = getattr(coins_base, "recover_journal", None)
        if recover is not None and recover():
            log_print("db", "chainstate commit journal replayed at startup")
        self.params = params
        self.chain = CChain()
        self.block_index: dict[bytes, CBlockIndex] = {}
        self.coins = CoinsCache(coins_base)
        self.block_store = block_store
        self.index_db = index_db  # BlockIndexDB or None (ephemeral nodes)
        self.script_verifier = script_verifier
        self.get_time = get_time
        self._candidates: set[CBlockIndex] = set()  # setBlockIndexCandidates
        self._seq = 0
        self._precious_seq = 0  # PreciousBlock's nBlockReverseSequenceId
        self._invalid: set[CBlockIndex] = set()
        # setDirtyBlockIndex analogue: indexes whose on-disk record is stale
        self._dirty_index: set[CBlockIndex] = set()
        # mapBlocksUnlinked analogue: children with data whose ancestor path
        # is missing data; relinked when the gap block arrives.
        self._unlinked: dict[CBlockIndex, list[CBlockIndex]] = {}
        # notification hooks (CMainSignals analogue — validationinterface)
        self.on_block_connected: list[Callable] = []
        self.on_block_disconnected: list[Callable] = []
        self.on_tip_changed: list[Callable] = []
        # cumulative ConnectBlock phase timings (ms) — the reference's
        # nTimeCheck/nTimeConnect/nTimeVerify/nTimeFlush statics
        # (src/validation.cpp:~1950-2080), surfaced via -debug=bench
        self.bench = {
            "check_ms": 0.0, "connect_ms": 0.0, "verify_ms": 0.0,
            "flush_ms": 0.0, "index_ms": 0.0, "blocks": 0,
        }
        # Pipelined IBD (the settle horizon): blocks are speculatively
        # connected — each into its own CoinsCache layer over the settled
        # cache — while their signature batches are still in flight on the
        # device; externalization (coins merge, undo write, index row,
        # tip/connect listeners) happens at settle time, oldest first, and
        # a settle failure drops every speculative layer (full unwind to
        # the pre-block coin set). depth <= 1 = serial engine. The node
        # runtime wires -pipelinedepth here; the Python IBD import loop is
        # the driver (node.py).
        self.pipeline_depth = 1
        # The speculation TREE (ISSUE 9, generalizing the PR 3 linear
        # horizon): block hash -> entry {idx, block, undo, layer, job,
        # scripts, parent, children, branch, t_connect}. Entries whose
        # ``parent`` is None are roots — children of the settled tip,
        # their layers based directly on the settled cache; every other
        # entry's layer stacks on its parent entry's layer. Competing
        # tips are sibling subtrees; the most-work branch settles in
        # order and losing sibling subtrees are dropped un-externalized.
        self._spec: dict[bytes, dict] = {}
        # -specbranches: cap on live leaves — a hostile peer fanning out
        # forks at the tip buys at most this much concurrent validation;
        # extra forks take the serial candidate path (cheap: they are
        # not most-work, so activation leaves them as candidates).
        self.max_branches = 4
        # -spechold: live-path settle grace (seconds). While the oldest
        # root is younger than this, settle_live() holds it speculative
        # so a competing tip arriving inside the window joins the tree
        # instead of forcing a serial reorg. 0 = settle eagerly (the
        # serial engine's externalization latency, default).
        self.spec_hold_s = 0.0
        # degradation ladder state: consecutive-unwind pressure collapses
        # the tree to single-branch (level 1) then serial (level 2) mode
        # rather than thrashing; sustained clean settles re-open it.
        self._unwind_streak = 0
        self._settles_since_unwind = 0
        self._activating = False  # recursion guard (activation <-> settle)
        self._packer = None  # ops/ecdsa_batch.LanePacker, built lazily
        # serving/sigservice.SigService (node wires it): block connects
        # run under its import_priority() so live mempool lanes dispatch
        # on the CPU lane while the block's own batches own the device
        self.sig_service = None
        self._settling = False  # reentrancy guard (flush <-> settle hooks)
        self.pipeline_stats = {
            "settled_blocks": 0, "unwinds": 0, "unwound_blocks": 0,
            "max_depth": 0, "scan_ms": 0.0, "settle_wait_ms": 0.0,
            "commit_ms": 0.0,
            # speculation-tree accounting (ISSUE 9)
            "branch_drops": 0, "dropped_blocks": 0,
            "branches_live_max": 0, "reorgs": 0, "reorg_depth_max": 0,
            "serial_linear_fallbacks": 0, "degraded_connects": 0,
        }
        # BIP30 pre-scan accounting: probes resolved from cache layers vs
        # the store, and whole scans skipped above the last checkpoint
        # (Core's BIP34-era exemption)
        self.bip30_stats = {
            "lookups": 0, "cache_resolved": 0,
            "skipped_scans": 0, "skipped_lookups": 0,
        }
        # settle-horizon stall sentinel (util/devicewatch, observe-only):
        # speculative blocks parked with no settle progress for the quiet
        # period = a wedged device settle. Registration replaces by name
        # (a fresh manager supersedes the old one's closure — the PR 6
        # collector pattern); the node re-registers with -watchdogquiet
        # and unregisters at close. The probe holds only a WEAKREF: a
        # bare manager (library use, tools) has no close path, and a
        # strong closure would pin its whole UTXO cache in the process-
        # global registry for the rest of the process.
        import weakref

        self_ref = weakref.ref(self)
        dw.WATCHDOG.register(
            "pipeline",
            pending_fn=lambda: (
                len(m._spec) if (m := self_ref()) is not None else 0))
        self._init_genesis()

    # ------------------------------------------------------------------
    # init
    # ------------------------------------------------------------------

    def _init_genesis(self):
        genesis = self.params.genesis
        gh = genesis.get_hash()
        if self.block_store.get_block(gh) is None:
            self.block_store.put_block(gh, genesis.serialize())
        idx = CBlockIndex(genesis.header, gh, None)
        idx.status = BlockStatus.VALID_SCRIPTS | BlockStatus.HAVE_DATA
        idx.n_tx = len(genesis.vtx)
        idx.chain_tx = idx.n_tx
        self.block_index[gh] = idx
        self._dirty_index.add(idx)
        best = self.coins.best_block()
        if best == b"\x00" * 32:
            # fresh chainstate: connect genesis outputs
            self.chain.set_tip(idx)
            for tx in genesis.vtx:
                add_coins(self.coins, tx, 0)
            self.coins.set_best_block(gh)
        # warm chainstate: the tip is restored by load_block_index(), which
        # the node runtime calls right after construction (LoadBlockIndexDB)

    def load_block_index(self) -> bool:
        """LoadBlockIndexDB (src/validation.cpp): rebuild the in-memory block
        tree, block-file positions, chain tip, and connect candidates from the
        index DB + the coins DB's best-block marker. Returns False when there
        is nothing to load (fresh datadir). Call once, right after __init__."""
        if self.index_db is None:
            return False
        entries = sorted(self.index_db.iterate_index(), key=lambda e: e[2])
        if not entries:
            return False
        max_seq = 0
        for h, header, height, status, n_tx, blkpos, undopos in entries:
            idx = self.block_index.get(h)
            if idx is None:
                prev = self.block_index.get(header.hash_prev_block)
                if prev is None and height != 0:
                    # orphaned index row (ancestor never flushed) — skip; the
                    # block data, if any, is recoverable via -reindex
                    continue
                idx = CBlockIndex(header, h, prev)
                self.block_index[h] = idx
            idx.status = BlockStatus(status)
            idx.n_tx = n_tx
            self._seq = max_seq = max_seq + 1
            idx.sequence_id = max_seq
            if idx.status & BlockStatus.HAVE_DATA:
                base = idx.prev.chain_tx if idx.prev is not None else 0
                if base > 0 or idx.prev is None:
                    idx.chain_tx = base + idx.n_tx
                else:
                    # repopulate mapBlocksUnlinked: data present but an
                    # ancestor's data is missing
                    self._unlinked.setdefault(idx.prev, []).append(idx)
            if blkpos is not None and hasattr(self.block_store, "positions"):
                self.block_store.positions[h] = blkpos
            if undopos is not None and hasattr(self.block_store, "undo_positions"):
                self.block_store.undo_positions[h] = undopos
        best = self.coins.best_block()
        tip = self.block_index.get(best)
        if tip is None:
            raise BlockValidationError(
                "chainstate-corrupt",
                f"best block {hash_to_hex(best)} not in block index (reindex required)",
            )
        self.chain.set_tip(tip)
        for idx in self.block_index.values():
            if not (idx.status & BlockStatus.FAILED_MASK):
                self._try_add_candidate(idx)
            else:
                self._invalid.add(idx)
        log_print("db", "LoadBlockIndexDB: %d entries, tip height %d",
                  len(entries), tip.height)
        return True

    # ------------------------------------------------------------------
    # context-free checks
    # ------------------------------------------------------------------

    def check_block_header(self, header: CBlockHeader, check_pow: bool = True) -> None:
        """CheckBlockHeader: proof of work only (src/validation.cpp)."""
        if check_pow and not check_proof_of_work(
            header.get_hash(), header.bits, self.params.consensus
        ):
            raise BlockValidationError("high-hash", "proof of work failed")

    def check_block(self, block: CBlock, check_pow: bool = True,
                    check_merkle: bool = True) -> None:
        """CheckBlock (src/validation.cpp): header + merkle + tx sanity."""
        self.check_block_header(block.header, check_pow)

        if check_merkle:
            # supervised chooser (ops/dispatch.block_merkle_root): device
            # tree-reduction for large blocks under the merkle circuit
            # breaker, byte-exact CPU reference otherwise/on fallback
            from ..ops.dispatch import block_merkle_root as _merkle

            root, mutated = _merkle(block)
            if root != block.header.hash_merkle_root:
                raise BlockValidationError("bad-txnmrklroot", "hashMerkleRoot mismatch")
            if mutated:
                raise BlockValidationError("bad-txns-duplicate", "duplicate transaction")

        if not block.vtx:
            raise BlockValidationError("bad-blk-length", "block with no transactions")
        if block.size() > self.params.max_block_size:
            raise BlockValidationError("bad-blk-length", "size limits failed")
        if not block.vtx[0].is_coinbase():
            raise BlockValidationError("bad-cb-missing", "first tx is not coinbase")
        for tx in block.vtx[1:]:
            if tx.is_coinbase():
                raise BlockValidationError("bad-cb-multiple", "more than one coinbase")
        for tx in block.vtx:
            try:
                check_transaction(tx)
            except TxValidationError as e:
                raise BlockValidationError(e.reason, f"tx {tx.txid_hex}") from e

    # ------------------------------------------------------------------
    # contextual checks
    # ------------------------------------------------------------------

    def contextual_check_block_header(self, header: CBlockHeader,
                                      prev: CBlockIndex) -> None:
        """ContextualCheckBlockHeader: difficulty, timestamps, checkpoints."""
        expected_bits = get_next_work_required(prev, header.time, self.params.consensus)
        if header.bits != expected_bits:
            raise BlockValidationError("bad-diffbits", "incorrect proof of work")
        if header.time <= prev.get_median_time_past():
            raise BlockValidationError("time-too-old", "block's timestamp is too early")
        if header.time > self.get_time() + MAX_FUTURE_BLOCK_TIME:
            raise BlockValidationError("time-too-new", "block timestamp too far in the future")
        height = prev.height + 1
        cp_hash = self.params.checkpoints.get(height)
        if cp_hash is not None and header.get_hash() != cp_hash:
            raise BlockValidationError("checkpoint-mismatch", f"height {height}")
        # Reject forks below the last checkpoint we have on the active chain —
        # GetLastCheckpoint + the bad-fork-prior-to-checkpoint rule.
        last_cp = self._last_checkpoint_height()
        if height < last_cp:
            raise BlockValidationError(
                "bad-fork-prior-to-checkpoint", f"height {height} < checkpoint {last_cp}"
            )

    def _last_checkpoint_height(self) -> int:
        """Height of the highest checkpoint present on the active chain —
        Checkpoints::GetLastCheckpoint (src/checkpoints.cpp)."""
        for h in sorted(self.params.checkpoints, reverse=True):
            idx = self.chain[h]
            if idx is not None and idx.hash == self.params.checkpoints[h]:
                return h
        return 0

    def test_block_validity(self, block: CBlock) -> None:
        """TestBlockValidity (src/validation.cpp:~3500): full non-PoW
        validation of a tip candidate on a throwaway view — header context
        (nBits/time), block rules, and a scripts-on connect dry-run.
        Raises BlockValidationError. The dry-run itself mutates nothing,
        but with a live speculation tree open it first settles the
        horizon (an externalization: tip listeners may fire) so the
        throwaway view and tip() agree on one coin state."""
        from .coins import CoinsCache

        # the dry-run connects against self.coins (settled) at tip() —
        # with a live tree open those disagree; settle to realign
        self.settle_horizon()
        tip = self.tip()
        self.check_block(block, check_pow=False)
        self.contextual_check_block_header(block.header, tip)
        self.contextual_check_block(block, tip)
        idx = CBlockIndex(block.header, block.get_hash(), tip)
        self.connect_block(block, idx, check_scripts=True,
                           view=CoinsCache(self.coins))

    def contextual_check_block(self, block: CBlock, prev: CBlockIndex) -> None:
        """ContextualCheckBlock: BIP34 height-in-coinbase, tx finality."""
        height = prev.height + 1
        mtp = prev.get_median_time_past()
        for tx in block.vtx:
            if not self._is_final_tx(tx, height, mtp):
                raise BlockValidationError("bad-txns-nonfinal", "non-final transaction")
        if height >= self.params.consensus.bip34_height:
            expect = _script_int(height)
            script_sig = block.vtx[0].vin[0].script_sig
            if script_sig[: len(expect)] != expect:
                raise BlockValidationError("bad-cb-height", "block height mismatch in coinbase")

    _is_final_tx = staticmethod(is_final_tx)

    # ------------------------------------------------------------------
    # header / block acceptance into the tree
    # ------------------------------------------------------------------

    def accept_block_header(self, header: CBlockHeader) -> CBlockIndex:
        """AcceptBlockHeader: check + insert into the block tree."""
        h = header.get_hash()
        existing = self.block_index.get(h)
        if existing is not None:
            if existing.status & BlockStatus.FAILED_MASK:
                raise BlockValidationError("duplicate", "block is marked invalid")
            return existing
        self.check_block_header(header)
        prev = self.block_index.get(header.hash_prev_block)
        if prev is None:
            raise BlockValidationError("prev-blk-not-found", hash_to_hex(header.hash_prev_block))
        if prev.status & BlockStatus.FAILED_MASK:
            raise BlockValidationError("bad-prevblk", "previous block invalid")
        self.contextual_check_block_header(header, prev)
        idx = CBlockIndex(header, h, prev)
        self._seq += 1
        idx.sequence_id = self._seq
        idx.raise_validity(BlockStatus.VALID_TREE)
        self.block_index[h] = idx
        self._dirty_index.add(idx)
        return idx

    def accept_block(self, block: CBlock) -> CBlockIndex:
        """AcceptBlock (src/validation.cpp:~3000): header + full block checks,
        persist to the block store, mark HAVE_DATA, register candidate."""
        idx = self.accept_block_header(block.header)
        if idx.status & BlockStatus.HAVE_DATA:
            return idx  # already have it
        self.check_block(block)
        self.contextual_check_block(block, idx.prev)
        idx.n_tx = len(block.vtx)
        idx.raise_validity(BlockStatus.VALID_TRANSACTIONS)
        idx.status |= BlockStatus.HAVE_DATA
        self.block_store.put_block(idx.hash, block.serialize())
        self._link_chain_tx(idx)
        self._dirty_index.add(idx)
        return idx

    def _link_chain_tx(self, idx: CBlockIndex):
        """ReceivedBlockTransactions (src/validation.cpp): propagate the
        nChainTx analogue down any now-complete subtree; blocks whose
        ancestry still lacks data park in _unlinked until the gap fills."""
        if idx.prev is not None and idx.prev.chain_tx == 0:
            self._unlinked.setdefault(idx.prev, []).append(idx)
            return
        queue = [idx]
        while queue:
            cur = queue.pop()
            base = cur.prev.chain_tx if cur.prev is not None else 0
            cur.chain_tx = base + cur.n_tx
            self._try_add_candidate(cur)
            queue.extend(self._unlinked.pop(cur, ()))

    def _try_add_candidate(self, idx: CBlockIndex):
        tip = self.chain.tip()
        if (
            idx.chain_tx > 0  # whole ancestor path has block data
            and idx.is_valid(BlockStatus.VALID_TRANSACTIONS)
            and (tip is None
                 or self._work_key(idx) > self._work_key(tip))
        ):
            self._candidates.add(idx)

    # ------------------------------------------------------------------
    # connect / disconnect
    # ------------------------------------------------------------------

    def connect_block(self, block: CBlock, idx: CBlockIndex,
                      check_scripts: bool = True,
                      view: Optional[CoinsCache] = None) -> BlockUndo:
        """ConnectBlock (src/validation.cpp:~1700).

        Edits go to `view` when given (dry-runs pass a throwaway layer and
        own it; _connect_tip passes a scratch it flushes itself). With no
        view, edits build on an internal scratch layer that is merged into
        self.coins ONLY on success — a failing connect can never corrupt the
        live cache. Returns undo data.
        """
        merge_on_success = view is None
        if view is None:
            view = CoinsCache(self.coins)
        coins_save, self.coins = self.coins, view
        try:
            undo = self._connect_block_inner(block, idx, check_scripts)
        finally:
            self.coins = coins_save
        if merge_on_success:
            view.flush()
        return undo

    def _connect_block_inner(self, block: CBlock, idx: CBlockIndex,
                             check_scripts: bool,
                             sig_jobs: Optional[list] = None,
                             branch: Optional[str] = None) -> BlockUndo:
        height = idx.height
        consensus = self.params.consensus

        # BIP30: no overwriting of existing unspent coins. Core's BIP34-era
        # exemption: above the last active-chain checkpoint (with BIP34
        # active) duplicate txids are impossible — coinbases commit to
        # their height — so the per-output scan is skipped outright. When
        # the scan does run, each probe resolves from cache layers when an
        # entry (live or tombstone) is resident, and otherwise pays only a
        # store EXISTENCE query — never a Coin fetch/deserialize, and never
        # a read-through entry polluting the -dbcache working set.
        b30 = self.bip30_stats
        last_cp = self._last_checkpoint_height()
        if (last_cp > 0 and height > last_cp
                and height >= consensus.bip34_height):
            b30["skipped_scans"] += 1
            b30["skipped_lookups"] += sum(len(tx.vout) for tx in block.vtx)
        else:
            for tx in block.vtx:
                txid = tx.txid
                for i in range(len(tx.vout)):
                    op = COutPoint(txid, i)
                    b30["lookups"] += 1
                    exists = self.coins.have_coin_cached(op)
                    if exists is None:
                        exists = self.coins.have_coin(op)
                    else:
                        b30["cache_resolved"] += 1
                    if exists:
                        raise BlockValidationError(
                            "bad-txns-BIP30", "tried to overwrite transaction")

        undo = BlockUndo([])
        fees = 0
        spent_per_tx: list[list] = []  # per non-coinbase tx: spent Coins, input order
        for tx in block.vtx:
            if tx.is_coinbase():
                add_coins(self.coins, tx, height)
                continue
            txundo = TxUndo([])
            value_in = 0
            for txin in tx.vin:
                coin = self.coins.spend_coin(txin.prevout)
                if coin is None:
                    raise BlockValidationError(
                        "bad-txns-inputs-missingorspent", f"tx {tx.txid_hex}"
                    )
                if coin.is_coinbase and height - coin.height < consensus.coinbase_maturity:
                    raise BlockValidationError(
                        "bad-txns-premature-spend-of-coinbase",
                        f"{height - coin.height} of {consensus.coinbase_maturity}",
                    )
                value_in += coin.out.value
                txundo.prevouts.append(coin)
            if not money_range(value_in):
                raise BlockValidationError("bad-txns-inputvalues-outofrange")
            value_out = tx.total_output_value()
            if value_in < value_out:
                raise BlockValidationError("bad-txns-in-belowout", f"tx {tx.txid_hex}")
            fee = value_in - value_out
            if not money_range(fee):
                raise BlockValidationError("bad-txns-fee-outofrange")
            fees += fee
            undo.vtxundo.append(txundo)
            spent_per_tx.append(txundo.prevouts)
            add_coins(self.coins, tx, height)

        reward = fees + get_block_subsidy(height, consensus)
        if block.vtx[0].total_output_value() > reward:
            raise BlockValidationError(
                "bad-cb-amount",
                f"coinbase pays too much ({block.vtx[0].total_output_value()} > {reward})",
            )

        if check_scripts and self.script_verifier is not None:
            # Deferred batch verification — the CCheckQueue replacement:
            # one call, one TPU dispatch (SURVEY.md §4.2 graft point).
            # With a sig_jobs sink (the pipelined engine) only the SCAN
            # stage runs here — records ship into the cross-block lane
            # packer and the settle stage happens at the horizon.
            tv = _time.perf_counter()
            scan = getattr(self.script_verifier, "scan", None)
            if sig_jobs is not None and scan is not None:
                sig_jobs.append(
                    scan(block, idx, spent_per_tx, packer=self._sig_packer(),
                         tag=branch)
                )
            else:
                self.script_verifier(block, idx, spent_per_tx)
            self.bench["verify_ms"] += (_time.perf_counter() - tv) * 1e3

        self.coins.set_best_block(idx.hash)
        return undo

    def disconnect_block(self, block: CBlock, idx: CBlockIndex,
                         undo: BlockUndo,
                         view: Optional[CoinsCache] = None) -> None:
        """DisconnectBlock: remove created coins, restore spent ones."""
        if view is not None:
            coins_save, self.coins = self.coins, view
            try:
                return self.disconnect_block(block, idx, undo)
            finally:
                self.coins = coins_save
        if len(undo.vtxundo) != len(block.vtx) - 1:
            raise BlockValidationError("bad-undo", "undo tx count mismatch")
        for tx in reversed(block.vtx):
            txid = tx.txid
            for i in range(len(tx.vout)):
                self.coins.spend_coin(COutPoint(txid, i))
        for tx, txundo in zip(reversed(block.vtx[1:]), reversed(undo.vtxundo)):
            if len(txundo.prevouts) != len(tx.vin):
                raise BlockValidationError("bad-undo", "undo input count mismatch")
            for txin, coin in zip(tx.vin, txundo.prevouts):
                self.coins.add_coin(txin.prevout, coin, overwrite=True)
        self.coins.set_best_block(idx.prev.hash)

    # ------------------------------------------------------------------
    # chain activation (reorg engine)
    # ------------------------------------------------------------------

    def _find_most_work_chain(self) -> Optional[CBlockIndex]:
        """FindMostWorkChain: best candidate not known to be invalid."""
        best = None
        for idx in self._candidates:
            if idx.status & BlockStatus.FAILED_MASK:
                continue
            if best is None or (self._work_key(idx)
                                > self._work_key(best)):
                best = idx
        return best

    def activate_best_chain(self) -> None:
        """ActivateBestChain (src/validation.cpp:~2500): step toward the
        most-work valid chain, disconnecting/connecting as needed. The
        comparison is CBlockIndexWorkComparator's (work, then earlier
        sequence wins) so preciousblock's negative sequence ids can win an
        equal-work tie; a later-received equal-work block still loses."""
        # settle-horizon barrier, enforced HERE and not just at the
        # pipelined entry point: serial activation walks and edits
        # self.coins directly, which is only the settled prefix while
        # speculative layers are open — any caller reaching this with an
        # open horizon (today none can; P2P/RPC start after the import
        # drains it) must first settle or the reorg engine would read a
        # coin set missing the speculative edits. No-op when empty or
        # when called back from within a settle.
        self.settle_horizon()
        activating_save, self._activating = self._activating, True
        try:
            while True:
                tip = self.chain.tip()
                target = self._find_most_work_chain()
                if target is None or (tip is not None and (
                    self._work_key(target) <= self._work_key(tip)
                )):
                    self._prune_candidates()
                    return
                if not self._activate_step(target):
                    # target (or an ancestor) failed validation; loop to
                    # retry with the next-best candidate
                    continue
                self._prune_candidates()
                for cb in self.on_tip_changed:
                    cb(self.chain.tip())
                # loop again: a better candidate may have appeared
        finally:
            self._activating = activating_save

    def _activate_step(self, target: CBlockIndex) -> bool:
        """One ActivateBestChainStep: reorg from current tip to target."""
        fork = self.chain.find_fork(target)
        # disconnect to the fork point
        n_disc = 0
        while self.chain.tip() is not None and self.chain.tip() is not fork:
            if not self._disconnect_tip():
                return False
            n_disc += 1
        self._note_reorg(n_disc, target)
        # connect the path fork -> target
        path = []
        idx = target
        while idx is not fork:
            path.append(idx)
            idx = idx.prev
        for idx in reversed(path):
            if not self._connect_tip(idx):
                return False
        return True

    def _note_reorg(self, depth: int, target: CBlockIndex) -> None:
        """Reorg observability: ``depth`` settled blocks were disconnected
        on the way to ``target`` (0 = plain extension, not a reorg)."""
        if depth <= 0:
            return
        ps = self.pipeline_stats
        ps["reorgs"] += 1
        ps["reorg_depth_max"] = max(ps["reorg_depth_max"], depth)
        _REORGS_C.inc()
        _REORG_DEPTH_H.observe(depth)
        tm.instant("block.reorg", depth=depth,
                   to_height=target.height,
                   to_hash=hash_to_hex(target.hash)[:16])
        log_print("bench", "reorg: %d block(s) disconnected toward %s "
                  "height=%d", depth, hash_to_hex(target.hash)[:16],
                  target.height)

    def script_checks_needed(self, idx: CBlockIndex) -> bool:
        """The fScriptChecks assumevalid gate (src/validation.cpp ConnectBlock):
        skip script verification for ancestors of the assume_valid block,
        provided that block is in our index and carries at least the params'
        minimum chain work — the single biggest reindex accelerator
        (SURVEY.md §6.4)."""
        av = self.params.assume_valid
        if not av:
            return True
        av_idx = self.block_index.get(av)
        if av_idx is None or not av_idx.is_valid(BlockStatus.VALID_TREE):
            return True
        if av_idx.chain_work < self.params.minimum_chain_work:
            return True
        return av_idx.get_ancestor(idx.height) is not idx

    def _connect_tip(self, idx: CBlockIndex) -> bool:
        """ConnectTip: load block, connect, update chain; on failure mark
        the subtree invalid and return False."""
        t0 = _time.perf_counter()
        raw = self.block_store.get_block(idx.hash)
        if raw is None:
            # Should be unreachable (chain_tx gating), but recover rather
            # than assert: drop the candidate and let the activation loop
            # pick the next-best chain.
            self._candidates.discard(idx)
            return False
        block = CBlock.from_bytes(raw)
        t1 = _time.perf_counter()
        check_scripts = self.script_checks_needed(idx)
        scratch = CoinsCache(self.coins)
        try:
            undo = self.connect_block(block, idx, check_scripts=check_scripts,
                                      view=scratch)
        except BlockValidationError:
            self._mark_invalid(idx)
            return False  # scratch layer dropped; earlier edits untouched
        t2 = _time.perf_counter()
        scratch.flush()  # merge into the long-lived cache
        self.block_store.put_undo(idx.hash, undo.serialize())
        idx.status |= BlockStatus.HAVE_UNDO
        idx.raise_validity(
            BlockStatus.VALID_SCRIPTS
            if (self.script_verifier and check_scripts)
            else BlockStatus.VALID_CHAIN
        )
        self._dirty_index.add(idx)
        self.chain.set_tip(idx)
        t3 = _time.perf_counter()
        b = self.bench
        b["check_ms"] += (t1 - t0) * 1e3
        b["connect_ms"] += (t2 - t1) * 1e3
        b["flush_ms"] += (t3 - t2) * 1e3
        b["blocks"] += 1
        log_print(
            "bench",
            "ConnectBlock %s height=%d txs=%d: read %.2fms connect %.2fms "
            "post %.2fms [cum: check %.2fms connect %.2fms flush %.2fms]",
            hash_to_hex(idx.hash)[:16], idx.height, len(block.vtx),
            (t1 - t0) * 1e3, (t2 - t1) * 1e3, (t3 - t2) * 1e3,
            b["check_ms"], b["connect_ms"], b["flush_ms"],
        )
        for cb in self.on_block_connected:
            cb(block, idx)
        return True

    def _disconnect_tip(self) -> bool:
        tip = self.chain.tip()
        raw = self.block_store.get_block(tip.hash)
        undo_raw = self.block_store.get_undo(tip.hash)
        assert raw is not None and undo_raw is not None
        block = CBlock.from_bytes(raw)
        scratch = CoinsCache(self.coins)
        self.disconnect_block(block, tip, BlockUndo.from_bytes(undo_raw), view=scratch)
        scratch.flush()
        self._dirty_index.add(tip)
        self.chain.set_tip(tip.prev)
        self._try_add_candidate(tip)  # it may become best again later
        for cb in self.on_block_disconnected:
            cb(block, tip)
        return True

    def _mark_invalid(self, idx: CBlockIndex):
        """InvalidBlockFound: FAILED_VALID on idx, FAILED_CHILD on descendants.
        Uses the O(log n) get_ancestor skip-list per index rather than a
        linear prev-walk (round-1/2 weak-item fix)."""
        idx.status |= BlockStatus.FAILED_VALID
        self._invalid.add(idx)
        self._candidates.discard(idx)
        self._dirty_index.add(idx)
        for other in self.block_index.values():
            if other is idx or other.height <= idx.height:
                continue
            if other.get_ancestor(idx.height) is idx:
                other.status |= BlockStatus.FAILED_CHILD
                self._candidates.discard(other)
                self._dirty_index.add(other)

    def _prune_candidates(self):
        tip = self.chain.tip()
        if tip is None:
            return
        self._candidates = {
            c for c in self._candidates
            if self._work_key(c) > self._work_key(tip)
            and not (c.status & BlockStatus.FAILED_MASK)
        }

    # ------------------------------------------------------------------
    # public entry points
    # ------------------------------------------------------------------

    def process_new_block(self, block: CBlock) -> bool:
        """ProcessNewBlock (src/validation.cpp:~3100). Returns True if the
        block was accepted into the tree (not necessarily the active chain).
        Raises BlockValidationError for invalid blocks (callers that need
        the reference's bool-only contract catch it)."""
        with self._import_priority():
            self.accept_block(block)
            self.activate_best_chain()
        return True

    def _import_priority(self):
        """Block-import preemption over the live signature service: while
        a connect is in flight, mempool lanes take the CPU path so the
        block's own batches keep the device (serving/sigservice)."""
        if self.sig_service is not None:
            return self.sig_service.import_priority()
        from contextlib import nullcontext

        return nullcontext()

    # ------------------------------------------------------------------
    # pipelined connect — the IBD settle horizon (overlaps the host scan,
    # the device signature settle, and the chainstate commit)
    # ------------------------------------------------------------------

    def settled_tip(self) -> Optional[CBlockIndex]:
        """The newest block whose signature batch has SETTLED — the only
        tip the outside world may observe (RPC getbestblockhash, P2P
        announcements, index flush). Equals chain.tip() whenever no
        speculative horizon is open."""
        for ent in self._spec.values():
            if ent["parent"] is None:
                return ent["idx"].prev
        return self.chain.tip()

    # -- speculation-tree shape queries ---------------------------------

    @property
    def _horizon(self) -> list[dict]:
        """The WINNING path of the speculation tree, root -> best leaf —
        the linear view PR 3 callers (tests, the watchdog probe, the
        flush barrier) reason about. Read-only by construction."""
        ent = self._best_spec_leaf()
        if ent is None:
            return []
        path = [ent]
        while path[-1]["parent"] is not None:
            path.append(self._spec[path[-1]["parent"]])
        path.reverse()
        return path

    @staticmethod
    def _work_key(idx: CBlockIndex) -> tuple:
        """CBlockIndexWorkComparator's key (work, then earlier-seen)."""
        return (idx.chain_work, -idx.sequence_id)

    def _spec_roots(self) -> list[dict]:
        return [e for e in self._spec.values() if e["parent"] is None]

    def _spec_leaves(self) -> list[dict]:
        return [e for e in self._spec.values() if not e["children"]]

    def _best_spec_leaf(self) -> Optional[dict]:
        """Entry holding the tree-wide most-work tip. chain_work is
        strictly increasing along a branch, so the global max is a leaf."""
        best = None
        for ent in self._spec.values():
            if best is None or (self._work_key(ent["idx"])
                                > self._work_key(best["idx"])):
                best = ent
        return best

    def _subtree(self, ent: dict) -> list[dict]:
        """``ent`` plus every descendant entry, parents-first."""
        out, queue = [], [ent]
        while queue:
            cur = queue.pop(0)
            out.append(cur)
            queue.extend(self._spec[h] for h in cur["children"]
                         if h in self._spec)
        return out

    def _subtree_best_key(self, ent: dict) -> tuple:
        return max(self._work_key(e["idx"]) for e in self._subtree(ent))

    def _winning_root(self) -> Optional[dict]:
        """The root whose subtree holds the most-work leaf — the next
        entry to settle."""
        best, best_key = None, None
        for root in self._spec_roots():
            key = self._subtree_best_key(root)
            if best is None or key > best_key:
                best, best_key = root, key
        return best

    def _settled_anchor(self) -> Optional[CBlockIndex]:
        """The settled tip computed WITHOUT consulting chain.tip() — safe
        mid-mutation (an unwind leaves chain.tip() pointing into the
        just-dropped branch until _retip runs): the tree's root anchor
        when branches are open, else the settled cache's best-block
        marker (the last flushed layer stamps it)."""
        for ent in self._spec.values():
            if ent["parent"] is None:
                return ent["idx"].prev
        idx = self.block_index.get(self.coins.best_block())
        return idx if idx is not None else self.chain.tip()

    def _retip(self) -> None:
        """Point the in-memory chain at the tree-wide best leaf (or the
        settled tip when nothing speculative beats it / the tree is
        empty) and refresh the tree gauges."""
        leaf = self._best_spec_leaf()
        settled = self._settled_anchor()
        if leaf is not None and (
                settled is None
                or self._work_key(leaf["idx"]) > self._work_key(settled)):
            self.chain.set_tip(leaf["idx"])
        elif settled is not None:
            self.chain.set_tip(settled)
        n_leaves = len(self._spec_leaves())
        _BRANCHES_G.set(n_leaves)
        _LAYERS_G.set(len(self._spec))
        ps = self.pipeline_stats
        ps["branches_live_max"] = max(ps["branches_live_max"], n_leaves)

    def _collapse_level(self) -> int:
        """The degradation ladder (0 = full tree, 1 = single branch,
        2 = serial). Driven by consecutive-unwind pressure — a branch
        that keeps failing at settle must not thrash layer churn — and
        by the ecdsa breaker: with the device path distrusted every lane
        goes to the CPU engine anyway, so concurrent branch validation
        only multiplies host work."""
        if self._unwind_streak >= 4:
            return 2
        level = 1 if self._unwind_streak >= 2 else 0
        try:
            from ..ops import dispatch

            if not dispatch.breaker("ecdsa").healthy():
                level = max(level, 1)
        except Exception:  # noqa: BLE001 — observability must not gate
            pass
        return level

    def _sig_packer(self):
        """The session's cross-block lane packer (ops/ecdsa_batch): fresh
        sigcheck records from every in-flight block aggregate into full
        padded device buckets instead of per-block partial dispatches."""
        if self._packer is None:
            from ..ops.ecdsa_batch import LanePacker

            self._packer = LanePacker(
                backend=getattr(self.script_verifier, "backend", "auto"),
                kernel=getattr(self.script_verifier, "kernel", None))
        return self._packer

    def process_new_block_pipelined(self, block: CBlock) -> bool:
        """ProcessNewBlock for the pipelined drivers (node.py import
        loop, P2P block flow). Any extension of the settled tip or of an
        in-tree entry is speculatively connected — UTXO edits into a
        fresh CoinsCache layer stacked per branch, undo retained,
        signature batch left in flight — competing tips validating
        concurrently as sibling subtrees (ISSUE 9). Backpressure settles
        the winning branch oldest-first once the winning path reaches
        pipeline_depth; losing branches drop at settle. Reorg candidates
        route through _activate_best_chain_pipelined (serial undo-based
        disconnects, tree-speculative reconnects); the degradation
        ladder (_collapse_level) narrows the tree to single-branch then
        serial mode under unwind pressure or an unhealthy ecdsa breaker.
        Same raise/return contract as process_new_block."""
        if self.pipeline_depth <= 1:
            return self.process_new_block(block)
        with self._import_priority():
            return self._process_new_block_pipelined_inner(block)

    def _process_new_block_pipelined_inner(self, block: CBlock) -> bool:
        idx = self.accept_block(block)
        if idx.hash in self._spec:
            return True  # already speculative (duplicate delivery)
        level = self._collapse_level()
        if level >= 2:
            # serial collapse: the tree has proven itself unhealthy —
            # drain it and run the reference engine until settles recover.
            # Successful serial activations count toward recovery too:
            # with no pipelined settles happening, nothing else could
            # ever re-open the tree.
            self.pipeline_stats["degraded_connects"] += 1
            tip_before = self.chain.tip()
            self.settle_horizon()
            self.activate_best_chain()
            if (self.chain.tip() is not tip_before
                    and not (idx.status & BlockStatus.FAILED_MASK)):
                self._settles_since_unwind += 1
                if self._settles_since_unwind >= 8:
                    self._unwind_streak = 0
            return True
        # backpressure: bound the WINNING path before connecting another
        # block (competing branches ride along, capped by max_branches)
        while len(self._horizon) >= self.pipeline_depth:
            if not self._settle_oldest():
                break  # unwound — idx's ancestry may now be invalid
        if not (idx.status & BlockStatus.FAILED_MASK):
            if self._speculatable(idx, level):
                if self._connect_tip_speculative(idx, block):
                    return True
                # scan-stage reject: fall through to the serial engine's
                # next-best-candidate retry, like a failed ConnectTip
            elif (idx.prev is self.chain.tip()
                    and self._find_most_work_chain() is idx):
                # invariant TRIPWIRE, not a live code path: by
                # construction _speculatable() accepts every linear
                # most-work extension at every collapse level, so this
                # counter stays 0 — the fork-storm acceptance run
                # asserts that, catching any future _speculatable
                # regression that would quietly re-serialize the fast
                # path
                self.pipeline_stats["serial_linear_fallbacks"] += 1
        # NOT an unconditional settle: a declined non-most-work fork must
        # leave the open tree alone (activation drains the horizon itself
        # exactly when a below-settled-tip reorg needs it)
        self._activate_best_chain_pipelined()
        return True

    def _speculatable(self, idx: CBlockIndex, level: int) -> bool:
        """May ``idx`` enter the speculation tree right now? Its parent
        must be the settled tip (a new root) or an in-tree entry; at
        collapse level >= 1 only a linear extension of the current best
        leaf qualifies; and a connect that would mint a new leaf beyond
        max_branches is declined (the serial candidate path is cheap for
        non-most-work forks)."""
        parent_ent = self._spec.get(idx.prev.hash) if idx.prev else None
        is_root = idx.prev is self.settled_tip()
        if not is_root and parent_ent is None:
            return False
        if level >= 1:
            # single-branch mode: only extend the winning leaf
            best = self._best_spec_leaf()
            if best is None:
                return is_root and not self._spec
            return parent_ent is best
        adds_leaf = is_root or bool(parent_ent["children"])
        if adds_leaf and len(self._spec_leaves()) + (1 if self._spec else 0) \
                > self.max_branches:
            return False
        return True

    def _connect_tip_speculative(self, idx: CBlockIndex,
                                 block: CBlock) -> bool:
        """ConnectTip minus externalization: edits land in a NEW CoinsCache
        layer stacked on the parent entry's layer (or the settled cache
        for a root), the script verifier runs its SCAN stage only, and the
        block's undo write, index row, validity raise, and listeners are
        all withheld until settle. On a scan-stage failure the layer is
        dropped and the block marked invalid — the serial _connect_tip
        verdict, just earlier. Competing tips land as sibling subtrees;
        their deferred records share the cross-block LanePacker, tagged
        with their branch for attribution."""
        t0 = _time.perf_counter()
        check_scripts = self.script_checks_needed(idx)
        parent_ent = self._spec.get(idx.prev.hash) if idx.prev else None
        if parent_ent is None and idx.prev is not self.settled_tip():
            # parent neither the settled tip nor in-tree: basing the
            # layer on self.coins would connect against the WRONG coin
            # state (a backpressure settle inside the activation path
            # loop can advance the settled tip past the fork point mid-
            # connect). Decline — the block is NOT invalid — and let the
            # caller's activation loop recompute fork/target against the
            # moved anchor.
            return False
        base = parent_ent["layer"] if parent_ent is not None else self.coins
        branch = (parent_ent["branch"] if parent_ent is not None
                  else hash_to_hex(idx.hash)[:12])
        layer = CoinsCache(base)
        jobs: list = []
        coins_save, self.coins = self.coins, layer
        # the scan span is the parent of this block's ecdsa.settle spans
        # (the batch captures trace_context() at dispatch) — trace_view
        # stitches scan end -> settle end into the per-block in-flight
        # window and measures the overlap fraction from it
        with tm.span("block.scan", height=idx.height,
                     hash=hash_to_hex(idx.hash)[:16]):
            try:
                undo = self._connect_block_inner(block, idx, check_scripts,
                                                 sig_jobs=jobs,
                                                 branch=branch)
            except BlockValidationError:
                for j in jobs:
                    j.drain()
                self._mark_invalid(idx)
                return False
            finally:
                self.coins = coins_save
        self._spec[idx.hash] = {
            "idx": idx, "block": block, "undo": undo, "layer": layer,
            "job": jobs[0] if jobs else None,
            "scripts": bool(check_scripts and self.script_verifier),
            "parent": parent_ent["idx"].hash if parent_ent else None,
            "children": [], "branch": branch,
            "t_connect": _time.monotonic(),
        }
        if parent_ent is not None:
            parent_ent["children"].append(idx.hash)
        self._retip()
        # prune like the serial engine does after every activation step —
        # without this, every imported block stays a candidate and the
        # per-block _find_most_work_chain scan turns a long linear IBD
        # quadratic (the candidate set must stay ~empty in steady state)
        self._prune_candidates()
        ps = self.pipeline_stats
        ps["max_depth"] = max(ps["max_depth"], len(self._horizon))
        ps["scan_ms"] += (_time.perf_counter() - t0) * 1e3
        _SCAN_H.observe(_time.perf_counter() - t0)
        # one speculative connect = forward progress: a branch stalled at
        # settle then shows pending-with-no-beat and the devicewatch
        # watchdog fires bcp_watchdog_stalled instead of IBD hanging mute
        dw.WATCHDOG.beat("pipeline")
        return True

    def _settle_oldest(self) -> bool:
        """Settle the winning branch's root block: wait for its signature
        batch, then externalize (coins merged into the settled cache,
        undo + index row written, VALID_SCRIPTS raised, connect/tip
        listeners fired) and drop every losing sibling subtree — their
        layers were stacked on the same settled cache the winner just
        flushed into, so once the winner externalizes they can never
        settle (reactivating one later is a real reorg, via undo data).
        Returns False when the batch failed — exactly the failing branch
        is unwound (byte-identical pre-fork coin set by construction)
        and the failing block marked invalid; sibling branches survive
        and the next call settles the new most-work branch."""
        ent = self._winning_root()
        if ent is None:
            return True
        idx = ent["idx"]
        settling_save, self._settling = self._settling, True
        try:
            t0 = _time.perf_counter()
            if ent["job"] is not None:
                try:
                    with tm.span("block.settle", height=idx.height,
                                 hash=hash_to_hex(idx.hash)[:16]):
                        ent["job"].settle()
                except BlockValidationError as e:
                    self._unwind_branch(ent, e)
                    return False
            t1 = _time.perf_counter()
            _SETTLE_H.observe(t1 - t0)
            with tm.span("block.commit", height=idx.height):
                # losing siblings first: their layers read through the
                # settled cache the winner is about to mutate
                for root in self._spec_roots():
                    if root is not ent:
                        self._drop_subtree(root, "lost-work")
                self._spec.pop(idx.hash)
                ent["layer"].flush()  # into the settled cache (self.coins)
                for child_h in ent["children"]:
                    child = self._spec.get(child_h)
                    if child is None:
                        continue
                    # re-base onto the settled cache — the old base is
                    # the (now empty) layer just flushed — and promote
                    # to root: the settled tip advanced onto ``idx``
                    child["layer"].base = self.coins
                    child["parent"] = None
                self.block_store.put_undo(idx.hash, ent["undo"].serialize())
                idx.status |= BlockStatus.HAVE_UNDO
                idx.raise_validity(
                    BlockStatus.VALID_SCRIPTS if ent["scripts"]
                    else BlockStatus.VALID_CHAIN
                )
                self._dirty_index.add(idx)
                ps = self.pipeline_stats
                ps["settled_blocks"] += 1
                ps["settle_wait_ms"] += (t1 - t0) * 1e3
                self._settles_since_unwind += 1
                if self._settles_since_unwind >= 8:
                    # sustained clean settles re-open the tree
                    self._unwind_streak = 0
                self.bench["blocks"] += 1
                self._retip()
                for cb in self.on_block_connected:
                    cb(ent["block"], idx)
                for cb in self.on_tip_changed:
                    cb(idx)
            ps["commit_ms"] += (_time.perf_counter() - t1) * 1e3
            _COMMIT_H.observe(_time.perf_counter() - t1)
            dw.WATCHDOG.beat("pipeline")  # one block settled = progress
            return True
        finally:
            self._settling = settling_save

    def _drop_subtree(self, root: dict, reason: str) -> None:
        """Drop one losing branch: drain its in-flight batches, discard
        its layers, and forget the entries. Nothing was externalized —
        the blocks stay HAVE_DATA candidates in the block index, so a
        later deep reorg can still activate them through the serial
        machinery (undo-based disconnects)."""
        entries = self._subtree(root)
        for ent in entries:
            if ent["job"] is not None:
                ent["job"].drain()
            self._spec.pop(ent["idx"].hash, None)
        for ent in entries:
            self._try_add_candidate(ent["idx"])
        ps = self.pipeline_stats
        ps["branch_drops"] += 1
        ps["dropped_blocks"] += len(entries)
        _BRANCH_DROPS_C.inc()
        lifetime_ms = (_time.monotonic() - root["t_connect"]) * 1e3
        tm.instant("block.branch_drop",
                   branch=root["branch"],
                   height=root["idx"].height,
                   hash=hash_to_hex(root["idx"].hash)[:16],
                   blocks=len(entries), reason=reason,
                   lifetime_ms=round(lifetime_ms, 3))
        log_print(
            "bench",
            "speculative branch dropped (%s): %d block(s) from %s "
            "height=%d, lived %.0f ms",
            reason, len(entries), hash_to_hex(root["idx"].hash)[:16],
            root["idx"].height, lifetime_ms,
        )

    def _unwind_branch(self, root: dict,
                       err: BlockValidationError) -> None:
        """A settle failure at a branch root: drop exactly that branch's
        subtree, drain its in-flight batches, mark the failing block
        invalid, and roll the in-memory tip back to the best surviving
        leaf (or the settled tip). The settled cache was never touched
        by the dropped layers, so the UTXO set is byte-identical to the
        pre-fork state by construction; sibling branches keep their
        layers and stay settleable."""
        failed = root["idx"]
        entries = self._subtree(root)
        for ent in entries:
            if ent["job"] is not None:
                ent["job"].drain()
            self._spec.pop(ent["idx"].hash, None)
        self._mark_invalid(failed)
        # roll the tip back FIRST: the candidate re-seed below compares
        # against chain.tip(), and a dormant fork the dead branch was
        # shadowing must pass that comparison (PR 3 ordering, kept)
        self._retip()
        # the tip ROLLED BACK: candidates pruned while it was ahead may be
        # viable again — re-seed from scratch, the invalidate_block recipe
        for other in self.block_index.values():
            self._try_add_candidate(other)
        ps = self.pipeline_stats
        ps["unwinds"] += 1
        ps["unwound_blocks"] += len(entries)
        self._unwind_streak += 1
        self._settles_since_unwind = 0
        _UNWINDS_C.inc(len(entries))
        # an unwind drains the branch — progress, not a stall
        dw.WATCHDOG.beat("pipeline")
        tm.instant("block.unwind", height=failed.height,
                   hash=hash_to_hex(failed.hash)[:16],
                   branch=root["branch"],
                   dropped=len(entries), reason=err.reason)
        log_print(
            "bench",
            "speculative branch unwound: %d block(s) dropped, "
            "%s invalid (%s)",
            len(entries), hash_to_hex(failed.hash)[:16], err.reason,
        )

    def _drain_spec(self) -> None:
        """Settle/unwind everything speculative WITHOUT the post-unwind
        activation retry — the internal barrier for activation steps
        (which own their candidate loop) and the body of the public
        settle_horizon."""
        while self._spec:
            self._settle_oldest()

    def settle_horizon(self) -> None:
        """Settle every speculative block, winning branch first — the
        barrier before any serial-path activation, reorg, external flush,
        or shutdown. Reentrancy-safe: a connect listener that triggers
        flush() mid-settle does not recurse. Like the serial engine, a
        failing block is marked invalid without raising; surviving
        branches keep settling (each failure drops at least one entry,
        so the loop terminates).

        An unwind can expose a DORMANT better candidate — a fork that
        was declined for speculation while the now-dead branch was
        ahead. Outside an activation (which owns its own candidate
        retry loop) the drain re-runs activation until quiescent, so a
        final-drain unwind converges exactly like the serial engine's
        failure retry would."""
        if self._settling:
            return
        while True:
            unwinds_before = self.pipeline_stats["unwinds"]
            self._drain_spec()
            if (self._activating
                    or self.pipeline_stats["unwinds"] == unwinds_before):
                return
            self._activate_best_chain_pipelined()

    def settle_live(self) -> None:
        """The live-traffic settle policy (P2P driver — per delivered
        block and again each connman tick): settle eagerly, EXCEPT hold
        (a) roots younger than ``spec_hold_s`` — the window in which a
        competing tip can still join the tree instead of forcing a
        serial reorg — and (b) equal-work branch ties, up to 10x the
        window, so a fork race resolves by work (or first-seen once the
        tie goes stale) rather than by arrival interleaving. With
        spec_hold_s == 0 (the default) this is an unconditional drain —
        serial-engine externalization latency. Like settle_horizon, an
        unwind re-runs activation afterwards: a dormant better candidate
        the dead branch was shadowing must not leave a quiet node
        serving a lower-work tip until the next block happens by."""
        if self._settling:
            return
        unwinds_before = self.pipeline_stats["unwinds"]
        while self._spec:
            if self.spec_hold_s > 0:
                now = _time.monotonic()
                win = self._winning_root()
                age = now - win["t_connect"]
                if age < self.spec_hold_s:
                    break
                roots = self._spec_roots()
                if len(roots) > 1:
                    keys = sorted((self._subtree_best_key(r) for r in roots),
                                  reverse=True)
                    tied = keys[0][0] == keys[1][0]  # equal WORK
                    if tied and age < 10 * self.spec_hold_s:
                        break
            self._settle_oldest()
        if (self.pipeline_stats["unwinds"] != unwinds_before
                and not self._activating):
            self._activate_best_chain_pipelined()

    def _activate_best_chain_pipelined(self) -> None:
        """ActivateBestChain with the connect leg running through the
        speculation tree: reorg disconnects stay serial (undo application
        against the settled cache — the horizon is drained first when the
        fork sits below the settled tip), but every path block toward the
        most-work candidate speculatively connects into tree layers, so
        deep reorgs, competing-branch activations, pre-checkpoint eras
        and -loadblock imports all ride the fast path. The horizon may be
        left OPEN on return — the caller's driver (import loop, P2P
        settle_live) owns the settle cadence."""
        activating_save, self._activating = self._activating, True
        try:
            while True:
                tip = self.chain.tip()
                target = self._find_most_work_chain()
                if target is None or (tip is not None and (
                    self._work_key(target) <= self._work_key(tip)
                )):
                    self._prune_candidates()
                    return
                if not self._activate_step_pipelined(target):
                    continue  # target (or ancestor) failed; retry next-best
                # tip/connect listeners fire at SETTLE (the
                # externalization point) — _settle_oldest owns them
                self._prune_candidates()
        finally:
            self._activating = activating_save

    def _activate_step_pipelined(self, target: CBlockIndex) -> bool:
        """One activation step toward ``target`` through the tree. The
        fork point decides the shape: at/above the settled tip nothing
        externalized moves (the new branch just joins the tree and the
        losers fall off at settle); below it, the horizon drains and
        settled blocks disconnect serially (metered as a real reorg)
        before the new path speculatively connects."""
        fork = self.chain.find_fork(target)
        settled = self.settled_tip()
        in_tree = fork is not None and (
            fork is settled or fork.hash in self._spec)
        if not in_tree:
            # direct drain, not settle_horizon: this step runs INSIDE the
            # activation loop, which owns the candidate retry — and the
            # serial disconnects below must never run with open layers
            self._drain_spec()
            # the drain may have unwound and MOVED the tip — the fork
            # point must be recomputed against the post-drain chain or
            # the disconnect walk below could sail past it
            fork = self.chain.find_fork(target)
            n_disc = 0
            while self.chain.tip() is not None \
                    and self.chain.tip() is not fork:
                if not self._disconnect_tip():
                    return False
                n_disc += 1
            self._note_reorg(n_disc, target)
        path = []
        idx = target
        while idx is not fork:
            path.append(idx)
            idx = idx.prev
        for idx in reversed(path):
            if idx.hash in self._spec:
                continue  # already speculative on this branch
            raw = self.block_store.get_block(idx.hash)
            if raw is None:
                self._candidates.discard(idx)
                return False
            block = CBlock.from_bytes(raw)
            while len(self._horizon) >= self.pipeline_depth:
                if not self._settle_oldest():
                    return False  # unwound — ancestry may now be invalid
            if not self._connect_tip_speculative(idx, block):
                return False
        return True

    def pipeline_snapshot(self) -> dict:
        """gettpuinfo's ``pipeline`` section: horizon depth/occupancy,
        per-leg cumulative times, unwind accounting, the cross-block
        lane packer's fill/overlap metrics, and the speculation tree's
        live shape (``tree``)."""
        ps = dict(self.pipeline_stats)
        ps["depth"] = self.pipeline_depth
        ps["in_horizon"] = len(self._horizon)
        packer = self._packer.snapshot() if self._packer is not None else {}
        ps["packer"] = packer
        ps["lane_fill_pct"] = packer.get("lane_fill_pct")
        ps["overlap_fraction"] = packer.get("overlap_fraction", 0.0)
        best = self._best_spec_leaf()
        ps["tree"] = {
            "layers": len(self._spec),
            "roots": len(self._spec_roots()),
            "branches": len(self._spec_leaves()),
            "branches_live_max": self.pipeline_stats["branches_live_max"],
            "max_branches": self.max_branches,
            "spec_hold_s": self.spec_hold_s,
            "best_leaf": (hash_to_hex(best["idx"].hash)[:16]
                          if best is not None else None),
            "branch_drops": self.pipeline_stats["branch_drops"],
            "dropped_blocks": self.pipeline_stats["dropped_blocks"],
            "reorgs": self.pipeline_stats["reorgs"],
            "reorg_depth_max": self.pipeline_stats["reorg_depth_max"],
            "collapse_level": self._collapse_level(),
            "unwind_streak": self._unwind_streak,
            "serial_linear_fallbacks":
                self.pipeline_stats["serial_linear_fallbacks"],
        }
        return ps

    def precious_block(self, idx: CBlockIndex) -> None:
        """PreciousBlock (src/validation.cpp:~2900): treat the block as if
        it had been received before every competitor — a decreasing
        negative sequence id wins the equal-work tie in the comparator."""
        if idx in self.chain:
            return  # already the active chain's block at its height
        self._precious_seq -= 1
        idx.sequence_id = self._precious_seq
        self._dirty_index.add(idx)
        self._try_add_candidate(idx)
        self.activate_best_chain()

    def invalidate_block(self, idx: CBlockIndex) -> None:
        """InvalidateBlock RPC backend: mark invalid and walk the tip back."""
        # settle first: with a live speculation tree open (-spechold) the
        # disconnect walk below needs on-disk undo data, which in-tree
        # blocks don't have yet
        self.settle_horizon()
        self._mark_invalid(idx)
        # disconnect while the invalid block is on the active chain
        while self.chain.tip() is not None and (
            self.chain[idx.height] is idx
        ):
            self._disconnect_tip()
        # re-seed candidates from scratch (conservative, matches semantics)
        for other in self.block_index.values():
            self._try_add_candidate(other)
        self.activate_best_chain()

    def reconsider_block(self, idx: CBlockIndex) -> None:
        """ResetBlockFailureFlags analogue (skip-list descendant test)."""
        for other in list(self.block_index.values()):
            if other is idx or (
                other.height >= idx.height and other.get_ancestor(idx.height) is idx
            ):
                other.status &= ~BlockStatus.FAILED_MASK
                self._invalid.discard(other)
                self._try_add_candidate(other)
                self._dirty_index.add(other)
        self.activate_best_chain()

    def flush(self) -> None:
        """FlushStateToDisk (src/validation.cpp:~1900). Write ordering is the
        crash-safety contract (SURVEY.md §6.3): (1) fsync block/undo files,
        (2) batch-write dirty block-index entries, (3) batch-write the coins
        cache + best-block marker in one transaction. A crash between (2) and
        (3) leaves index entries ahead of the chainstate; on restart those
        blocks are re-activated from their on-disk data."""
        # settle-horizon barrier: nothing speculative may reach disk. The
        # coins cache only ever holds settled edits (speculative blocks
        # live in their own layers), so this is about completeness — a
        # flush called mid-settle (via a connect listener) skips the
        # barrier and persists the settled prefix, which is always safe.
        self.settle_horizon()
        t0 = _time.perf_counter()
        self.block_store.flush()
        self.flush_index()
        self.coins.flush()
        self.bench["index_ms"] += (_time.perf_counter() - t0) * 1e3

    def flush_index(self) -> None:
        """Step (2) of the flush contract alone: batch-write dirty block
        index entries. The native fast-import path orders its own coins
        batch after this (node.py _fast_flush). Rows for blocks still
        inside the settle horizon are withheld — an index flush is a tip
        externalization, and nothing past the horizon is externalized
        until its signature batch settles (they re-dirty at settle)."""
        if self.index_db is not None and self._dirty_index:
            # hold EVERY tree entry, not just the winning path — no
            # speculative block's row may externalize pre-settle
            hold = {ent["idx"] for ent in self._spec.values()}
            flushable = [idx for idx in self._dirty_index
                         if idx not in hold]
            positions = getattr(self.block_store, "positions", {})
            undo_positions = getattr(self.block_store, "undo_positions", {})
            entries = [
                (
                    idx.hash,
                    idx.header.serialize(),
                    idx.height,
                    int(idx.status),
                    idx.n_tx,
                    positions.get(idx.hash),
                    undo_positions.get(idx.hash),
                )
                for idx in flushable
            ]
            if entries:
                self.index_db.put_index_batch(entries)
            self._dirty_index.difference_update(flushable)

    # -- queries used by RPC / mining --

    def tip(self) -> Optional[CBlockIndex]:
        return self.chain.tip()

    def get_block(self, block_hash: bytes) -> Optional[CBlock]:
        raw = self.block_store.get_block(block_hash)
        return CBlock.from_bytes(raw) if raw is not None else None


# BIP34 height encoding = CScript() << nHeight. Delegates to the script
# layer's script_int, which emits OP_1..OP_16/OP_0 single-byte opcodes for
# small values exactly as the reference's CScript operator<< does — a raw
# pushdata for 1..16 would make early regtest coinbases (bip34_height=0)
# incompatible with reference nodes.
_script_int = script_int
