"""Per-block script verification — the ConnectBlock sigcheck graft point.

Reference: src/validation.cpp:~1250 (CScriptCheck::operator()), :~1300
(CheckInputs), and the CCheckQueue fan-out in ConnectBlock (:~1700,
control.Add/Wait). The thread-pool barrier becomes: run the (cheap,
branchy) script interpreter on host with a DeferringSignatureChecker,
accumulate every OP_CHECKSIG into SigCheckRecords, then settle the whole
block in ONE ops/ecdsa_batch dispatch (SURVEY.md §4.2 graft point).
Failure attribution maps the failing lane back to (tx, input).

Sigcache-verified records are skipped before packing (sigcache.cpp:~70
semantics); fresh records are inserted after a successful batch.
"""

from __future__ import annotations

from typing import Optional

from ..consensus.params import ChainParams
from ..ops import ecdsa_batch
from ..crypto.hashes import hash160
from ..util import telemetry as tm
from ..script.interpreter import (
    SCRIPT_ENABLE_SIGHASH_FORKID,
    SCRIPT_VERIFY_CLEANSTACK,
    SCRIPT_VERIFY_MINIMALDATA,
    SCRIPT_VERIFY_NONE,
    SCRIPT_VERIFY_P2SH,
    SCRIPT_VERIFY_SIGPUSHONLY,
    SCRIPT_VERIFY_STRICTENC,
    SCRIPT_VERIFY_CHECKLOCKTIMEVERIFY,
    SCRIPT_VERIFY_CHECKSEQUENCEVERIFY,
    SCRIPT_VERIFY_DERSIG,
    SCRIPT_VERIFY_LOW_S,
    SCRIPT_VERIFY_NULLDUMMY,
    SCRIPT_VERIFY_NULLFAIL,
    DeferringSignatureChecker,
    ScriptError,
    SigCheckRecord,
    TransactionSignatureChecker,
    VerifyScript,
    check_pubkey_encoding,
    check_signature_encoding,
)
from ..script.sighash import SighashCache
from .sigcache import SignatureCache

# flags whose semantics the P2PKH fast path does not model — any of them
# present forces the generic interpreter (block consensus flags never set
# these; they are policy/test-only)
_FAST_PATH_EXCLUDES = (
    SCRIPT_VERIFY_MINIMALDATA
    | SCRIPT_VERIFY_CLEANSTACK
    | SCRIPT_VERIFY_SIGPUSHONLY
)


def _p2pkh_template(script_sig: bytes, spk: bytes):
    """Detect the standard P2PKH spend shape — the overwhelmingly dominant
    input form during a reindex. Returns (sig, pubkey) or None (anything
    unusual falls back to the generic interpreter).

    spk must be exactly OP_DUP OP_HASH160 <20> OP_EQUALVERIFY OP_CHECKSIG;
    scriptSig exactly two direct pushes (0x01-0x4b length opcodes, or OP_0
    for an empty item) with no trailing bytes."""
    if (len(spk) != 25 or spk[0] != 0x76 or spk[1] != 0xA9 or spk[2] != 20
            or spk[23] != 0x88 or spk[24] != 0xAC):
        return None
    ss = script_sig

    def read_push(pos: int):
        if pos >= len(ss):
            return None
        op = ss[pos]
        if op == 0:
            return b"", pos + 1
        if 1 <= op <= 75:
            end = pos + 1 + op
            if end > len(ss):
                return None
            return ss[pos + 1:end], end
        return None

    got = read_push(0)
    if got is None:
        return None
    sig, pos = got
    got = read_push(pos)
    if got is None:
        return None
    pub, pos = got
    if pos != len(ss):
        return None
    return sig, pub


def _p2pkh_fast_verify(sig: bytes, pub: bytes, spk: bytes, flags: int,
                       checker) -> None:
    """The exact EvalScript outcome for the P2PKH template without the
    generic opcode machinery: DUP/HASH160/EQUALVERIFY collapse to one
    hash160 compare, then the OP_CHECKSIG tail verbatim (same helper
    functions, same error codes, same NULLFAIL/final-truthiness rules as
    interpreter.py:~653). Raises ScriptError exactly where the generic
    path would; returns on success."""
    if hash160(pub) != spk[3:23]:
        raise ScriptError("equalverify")
    check_signature_encoding(sig, flags)
    check_pubkey_encoding(pub, flags)
    ok = checker.check_sig(sig, pub, spk, flags)
    if not ok:
        if (flags & SCRIPT_VERIFY_NULLFAIL) and sig:
            raise ScriptError("sig-nullfail")
        raise ScriptError("eval-false")


def block_script_flags(height: int, block_time: int,
                       params: ChainParams) -> int:
    """Consensus flags for a block at (height, time) — the reference
    derives these era-by-era in ConnectBlock (validation.cpp:~1700):
    P2SH by the BIP16 switch TIME, strict DER at BIP66, CLTV at BIP65,
    CSV at its height, and the fork's UAHF bundle [fork-delta, hedged].
    Historical blocks MUST get historical flags — applying today's
    STRICTENC to 2011 blocks (hybrid pubkeys, loose DER) would reject
    the real chain during reindex."""
    flags = SCRIPT_VERIFY_NONE
    c = params.consensus
    if block_time >= c.bip16_time:
        flags |= SCRIPT_VERIFY_P2SH
    if c.bip66_height >= 0 and height >= c.bip66_height:
        flags |= SCRIPT_VERIFY_DERSIG
    if c.bip65_height >= 0 and height >= c.bip65_height:
        flags |= SCRIPT_VERIFY_CHECKLOCKTIMEVERIFY
    if c.csv_height >= 0 and height >= c.csv_height:
        flags |= SCRIPT_VERIFY_CHECKSEQUENCEVERIFY
    if c.uahf_height >= 0 and height >= c.uahf_height:
        # post-fork: replay-protected sighash, strict encodings, and the
        # batch-soundness pair (NULLFAIL enables sig deferral)
        flags |= (
            SCRIPT_ENABLE_SIGHASH_FORKID
            | SCRIPT_VERIFY_STRICTENC
            | SCRIPT_VERIFY_NULLFAIL
            | SCRIPT_VERIFY_LOW_S
            | SCRIPT_VERIFY_NULLDUMMY
        )
    return flags


class _InlineCountingChecker(TransactionSignatureChecker):
    """Host-side inline sigcheck (pre-NULLFAIL eras) with BatchStats
    metering, so gettpuinfo can report how many sigops bypassed the TPU."""

    def check_sig(self, sig, pubkey, script_code, flags, defer_ok=True):
        ecdsa_batch.STATS.inline_legacy_sigs += 1
        return super().check_sig(sig, pubkey, script_code, flags, defer_ok)


class BlockSigJob:
    """The settle-stage handle for one block's deferred signature checks
    (the pipelined IBD engine's unit of in-flight work, ISSUE 4).

    Produced by BlockScriptVerifier.scan(); carries the block's deferred
    SigCheckRecords, their (tx, input) attribution, and the in-flight
    dispatches (BatchHandles on the serial path, SigBatchFutures when a
    cross-block LanePacker aggregated the lanes). settle() blocks until
    every dispatch reports, raises BlockValidationError with (tx, input)
    attribution on the first bad lane, and inserts the fresh sigcache
    keys only on full success — identical verdict semantics to the old
    synchronous __call__."""

    __slots__ = ("verifier", "block", "records", "rec_attr", "pending",
                 "settled")

    def __init__(self, verifier, block):
        self.verifier = verifier
        self.block = block
        self.records: list[SigCheckRecord] = []
        self.rec_attr: list[tuple[int, int]] = []  # (tx_index, input_index)
        # in-flight chunks: (record_indices, sigcache_keys, handle/future)
        self.pending: list[tuple[list[int], list, object]] = []
        self.settled = False

    def settle(self) -> None:
        """Block until every in-flight chunk reports; raise on failure."""
        from .chainstate import BlockValidationError

        if self.settled:
            return
        try:
            while self.pending:
                fresh, keys, handle = self.pending.pop(0)
                try:
                    ok = handle.result()
                except (KeyboardInterrupt, SystemExit,
                        NameError, AttributeError, UnboundLocalError):
                    raise  # programming errors must surface, not degrade
                except Exception:
                    # settle-time failure the handle could not self-heal:
                    # the verdict is a fresh forced-CPU verification of
                    # this chunk's records — never a cached phantom
                    ecdsa_batch.STATS.fault_fallback_sigs += len(fresh)
                    ok = ecdsa_batch.dispatch_batch(
                        [self.records[k] for k in fresh], backend="cpu"
                    ).result()
                for lane, k in enumerate(fresh):
                    if not ok[lane]:
                        t, i = self.rec_attr[k]
                        tx = self.block.vtx[t]
                        raise BlockValidationError(
                            "blk-bad-inputs",
                            "signature verification failed "
                            f"tx {tx.txid_hex} input {i}",
                        )
                for key in keys:
                    self.verifier.sigcache.add(key)
        finally:
            if self.pending:
                self.drain()
            self.settled = True

    def drain(self) -> None:
        """Abort-path settle: materialize every remaining handle so
        STATS.in_flight (and a breaker half-open probe riding one of them)
        never strands; verdicts are ignored."""
        while self.pending:
            _fresh, _keys, handle = self.pending.pop(0)
            drain = getattr(handle, "drain", None)  # SigBatchFuture: also
            try:                                    # discards parked lanes
                drain() if drain is not None else handle.result()
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:  # noqa: BLE001 — abort-path drain
                pass
        self.settled = True


class BlockScriptVerifier:
    """The ChainstateManager ``script_verifier`` hook (chainstate.py).

    Call contract: (block, idx, spent_per_tx) — spent_per_tx[i] is the
    list of spent Coins for block.vtx[i+1]'s inputs, input order. Raises
    BlockValidationError (via chainstate's exception type) on any failure.

    Pipelined callers split the call into scan() (host script
    interpretation, sigcache probe, dispatch/enqueue) and
    BlockSigJob.settle() (device settlement) so the settle horizon can
    keep connecting blocks while earlier batches are in flight.
    """

    def __init__(self, params: ChainParams, backend: str = "auto",
                 sigcache: Optional[SignatureCache] = None,
                 chunk: int = 4094, kernel: Optional[str] = None):
        self.params = params
        self.backend = backend
        # -ecdsakernel wiring (no semantic change — the dispatch layer owns
        # kernel selection/fallback; None defers to the process default)
        self.kernel = kernel
        self.sigcache = sigcache if sigcache is not None else SignatureCache()
        # P3 pipeline overlap (SURVEY.md §3.2): once this many deferred
        # records accumulate, dispatch them to the chip WITHOUT waiting and
        # keep interpreting the remaining transactions — host script work
        # and device ECDSA verify run concurrently (JAX async dispatch as
        # the CCheckQueue worker pool). Settlement at the end preserves the
        # all-or-nothing block verdict and failure attribution.
        # bucket-2 sizing: the supervised dispatch appends 2 known-answer
        # lanes per batch (ops/ecdsa_batch), so an exact-pow2 chunk would
        # spill into the next (1.5x) compiled bucket every time.
        self.chunk = chunk

    def __call__(self, block, idx, spent_per_tx) -> None:
        # serial engine: scan+settle back to back — spanned here so the
        # trace still shows the two legs (the pipelined engine's spans
        # live in chainstate, around the speculative connect / horizon
        # settle, and do not pass through __call__)
        with tm.span("block.scan", height=idx.height):
            job = self.scan(block, idx, spent_per_tx)
        with tm.span("block.settle", height=idx.height):
            job.settle()

    def scan(self, block, idx, spent_per_tx, packer=None,
             tag=None) -> BlockSigJob:
        """The SCAN stage: host script interpretation over every input,
        deferring OP_CHECKSIG into SigCheckRecords, probing the sigcache,
        and shipping fresh records — to ecdsa_batch.dispatch_batch chunks
        directly (serial path), or into the shared cross-block ``packer``
        (pipelined path), which banks them for full-bucket dispatches and
        hands back per-block futures. ``tag`` names the speculation-tree
        branch the block rides (packer lane attribution — competing
        branches share device buckets and the per-branch lane split is
        the observability for that). Raises BlockValidationError on any
        script failure; signature verdicts arrive at job.settle()."""
        from .chainstate import BlockValidationError

        flags = block_script_flags(
            idx.height, block.header.time, self.params
        )
        defer = bool(flags & SCRIPT_VERIFY_NULLFAIL)

        job = BlockSigJob(self, block)
        records = job.records
        rec_attr = job.rec_attr
        dispatched = 0

        def dispatch_from(start: int) -> int:
            """Sigcache-probe records[start:] and enqueue the fresh ones.

            The dispatch layer (ops/ecdsa_batch + ops/dispatch) owns the
            breaker/fault policy and falls back to the CPU engine
            internally; the extra try here is the last line of defense —
            if the supervision layer ITSELF raises, the batch must not be
            silently dropped: the verdict comes from a fresh forced-CPU
            verification, metered as a fault fallback."""
            keys = [
                SignatureCache.entry_key(r.msg_hash, r.r, r.s, r.pubkey,
                                         r.algo)
                for r in records[start:]
            ]
            fresh = [
                start + j for j, key in enumerate(keys)
                if not self.sigcache.contains(key)
            ]
            ecdsa_batch.STATS.sigcache_hits += (
                len(records) - start - len(fresh)
            )
            if fresh:
                batch = [records[k] for k in fresh]
                if packer is not None:
                    handle = packer.add(batch, tag=tag)
                else:
                    try:
                        handle = ecdsa_batch.dispatch_batch(
                            batch, backend=self.backend, kernel=self.kernel
                        )
                    except (KeyboardInterrupt, SystemExit,
                            NameError, AttributeError, UnboundLocalError):
                        raise  # programming errors surface, not degrade
                    except Exception:
                        ecdsa_batch.STATS.fault_fallback_sigs += len(batch)
                        handle = ecdsa_batch.dispatch_batch(batch,
                                                            backend="cpu")
                job.pending.append(
                    (fresh, [keys[k - start] for k in fresh], handle)
                )
            return len(records)

        assert len(spent_per_tx) == len(block.vtx) - 1, "spent coins mismatch"
        try:
            for t, (tx, spent) in enumerate(
                zip(block.vtx[1:], spent_per_tx), start=1
            ):
                cache = SighashCache(tx)
                for i, (txin, coin) in enumerate(zip(tx.vin, spent)):
                    if defer:
                        n_before = len(records)
                        checker = DeferringSignatureChecker(
                            tx, i, coin.out.value, records, cache
                        )
                    else:
                        # pre-NULLFAIL blocks: deferral unsound, verify inline
                        checker = _InlineCountingChecker(
                            tx, i, coin.out.value, cache
                        )
                    fast = (
                        _p2pkh_template(txin.script_sig,
                                        coin.out.script_pubkey)
                        if not flags & _FAST_PATH_EXCLUDES else None
                    )
                    try:
                        if fast is not None:
                            ecdsa_batch.STATS.p2pkh_fast_path += 1
                            _p2pkh_fast_verify(
                                fast[0], fast[1], coin.out.script_pubkey,
                                flags, checker
                            )
                        else:
                            VerifyScript(
                                txin.script_sig, coin.out.script_pubkey,
                                flags, checker
                            )
                    except ScriptError as e:
                        raise BlockValidationError(
                            "blk-bad-inputs",
                            f"script failure ({e.code}) "
                            f"tx {tx.txid_hex} input {i}",
                        ) from e
                    if defer:
                        rec_attr.extend(
                            (t, i) for _ in range(len(records) - n_before)
                        )
                # overlap point: enough records banked -> ship a chunk now
                if len(records) - dispatched >= self.chunk:
                    dispatched = dispatch_from(dispatched)

            if dispatched < len(records):
                dispatch_from(dispatched)
        except BaseException:
            # a script failure aborts the block mid-scan: drain the handles
            # already in flight so STATS.in_flight doesn't leak phantom
            # dispatches into gettpuinfo
            job.drain()
            raise
        return job
