"""Validation engine (L4) — the consensus state machine.

Mirrors the function inventory of src/validation.{h,cpp} (SURVEY.md §3.1):
ProcessNewBlock / AcceptBlock / ConnectBlock / DisconnectBlock /
ActivateBestChain / FlushStateToDisk over a layered UTXO view
(coins.py ← store/), with undo data for reorgs.

Host-side orchestration is Python (single asyncio-friendly thread; the
reference's cs_main lock has no equivalent because there is no shared-memory
threading here); the compute-bound legs — header PoW batches, Merkle roots,
signature batches — dispatch to ops/ kernels.
"""

from .chain import BlockStatus, CBlockIndex, CChain
from .coins import Coin, CoinsCache
from .chainstate import ChainstateManager

__all__ = [
    "BlockStatus",
    "CBlockIndex",
    "CChain",
    "Coin",
    "CoinsCache",
    "ChainstateManager",
]
