"""Block index and active chain.

Reference: src/chain.{h,cpp} (CBlockIndex, CChain, GetSkipHeight /
CBlockIndex::GetAncestor skip-list, GetMedianTimePast), src/chain.cpp:~120
(GetBlockProof via pow.get_block_proof).
"""

from __future__ import annotations

from enum import IntFlag
from typing import Optional

from ..consensus.block import CBlockHeader
from ..consensus.pow import get_block_proof

MEDIAN_TIME_SPAN = 11  # CBlockIndex::nMedianTimeSpan


class BlockStatus(IntFlag):
    """Validity progression + data flags — enum BlockStatus (src/chain.h)."""

    VALIDITY_UNKNOWN = 0
    VALID_HEADER = 1  # PoW + header sanity
    VALID_TREE = 2  # parent found, contextual header rules
    VALID_TRANSACTIONS = 3  # CheckBlock passed (merkle, tx sanity)
    VALID_CHAIN = 4  # ConnectBlock non-script rules passed
    VALID_SCRIPTS = 5  # full script/signature validation
    VALID_MASK = 7
    HAVE_DATA = 8
    HAVE_UNDO = 16
    FAILED_VALID = 32
    FAILED_CHILD = 64
    FAILED_MASK = FAILED_VALID | FAILED_CHILD


def _skip_height(height: int) -> int:
    """GetSkipHeight (src/chain.cpp:~70): pointer-jump target making
    get_ancestor O(log n). Exact reference formula."""
    if height < 2:
        return 0

    def invert_lowest_one(n: int) -> int:
        return n & (n - 1)

    if height & 1:
        return invert_lowest_one(invert_lowest_one(height - 1)) + 1
    return invert_lowest_one(height)


class CBlockIndex:
    """One entry of the in-memory block tree — CBlockIndex (src/chain.h)."""

    __slots__ = (
        "header",
        "hash",
        "prev",
        "skip",
        "height",
        "chain_work",
        "status",
        "n_tx",
        "chain_tx",
        "sequence_id",
    )

    def __init__(self, header: CBlockHeader, block_hash: Optional[bytes] = None,
                 prev: Optional["CBlockIndex"] = None):
        self.header = header
        self.hash = block_hash if block_hash is not None else header.get_hash()
        self.prev = prev
        self.height = 0 if prev is None else prev.height + 1
        self.skip: Optional[CBlockIndex] = (
            None if prev is None else prev.get_ancestor(_skip_height(self.height))
        )
        self.chain_work = (0 if prev is None else prev.chain_work) + get_block_proof(
            header.bits
        )
        self.status = BlockStatus.VALIDITY_UNKNOWN
        self.n_tx = 0
        # nChainTx analogue: cumulative tx count genesis..here; 0 means some
        # ancestor (or this block) is missing data — such indexes must NOT
        # become connect candidates (the reference gates
        # setBlockIndexCandidates on nChainTx, src/validation.cpp).
        self.chain_tx = 0
        self.sequence_id = 0  # tie-break: earlier-received wins (validation.cpp)

    # -- reference accessors --

    @property
    def time(self) -> int:
        return self.header.time

    @property
    def bits(self) -> int:
        return self.header.bits

    def get_ancestor(self, height: int) -> Optional["CBlockIndex"]:
        """CBlockIndex::GetAncestor — skip-list walk, O(log n)."""
        if height > self.height or height < 0:
            return None
        walk = self
        while walk.height > height:
            hs = _skip_height(walk.height)
            if walk.skip is not None and (
                hs == height
                or (
                    hs > height
                    and not (
                        walk.height - hs < walk.height - height
                        and hs < height + (walk.height - height) // 2
                    )
                )
            ):
                walk = walk.skip
            else:
                walk = walk.prev
        return walk

    def get_median_time_past(self) -> int:
        """Median of the last 11 block times — GetMedianTimePast."""
        times = []
        idx = self
        for _ in range(MEDIAN_TIME_SPAN):
            if idx is None:
                break
            times.append(idx.time)
            idx = idx.prev
        times.sort()
        return times[len(times) // 2]

    def is_valid(self, up_to: BlockStatus = BlockStatus.VALID_TRANSACTIONS) -> bool:
        """IsValid(nUpTo) — validity reached and not failed."""
        if self.status & BlockStatus.FAILED_MASK:
            return False
        return (self.status & BlockStatus.VALID_MASK) >= up_to

    def raise_validity(self, up_to: BlockStatus) -> bool:
        if self.status & BlockStatus.FAILED_MASK:
            return False
        if (self.status & BlockStatus.VALID_MASK) < up_to:
            self.status = (self.status & ~BlockStatus.VALID_MASK) | up_to
            return True
        return False

    def __repr__(self):
        return f"CBlockIndex(height={self.height}, hash={self.hash[::-1].hex()[:16]}...)"


class CChain:
    """The active chain as a height-indexed vector — CChain (src/chain.h)."""

    def __init__(self):
        self._chain: list[CBlockIndex] = []

    def genesis(self) -> Optional[CBlockIndex]:
        return self._chain[0] if self._chain else None

    def tip(self) -> Optional[CBlockIndex]:
        return self._chain[-1] if self._chain else None

    def __getitem__(self, height: int) -> Optional[CBlockIndex]:
        if 0 <= height < len(self._chain):
            return self._chain[height]
        return None

    def __contains__(self, index: CBlockIndex) -> bool:
        return self[index.height] is index

    def height(self) -> int:
        return len(self._chain) - 1

    def set_tip(self, index: Optional[CBlockIndex]) -> None:
        """CChain::SetTip — rebuild the vector back from the new tip."""
        if index is None:
            self._chain = []
            return
        self._chain += [None] * (index.height + 1 - len(self._chain))
        del self._chain[index.height + 1:]
        while index is not None and self._chain[index.height] is not index:
            self._chain[index.height] = index
            index = index.prev

    def next(self, index: CBlockIndex) -> Optional[CBlockIndex]:
        if index in self:
            return self[index.height + 1]
        return None

    def find_fork(self, index: Optional[CBlockIndex]) -> Optional[CBlockIndex]:
        """CChain::FindFork — last common ancestor with the active chain."""
        if index is None:
            return None
        if index.height > self.height():
            index = index.get_ancestor(self.height())
        while index is not None and index not in self:
            index = index.prev
        return index

    def get_locator(self, index: Optional[CBlockIndex] = None) -> list[bytes]:
        """CChain::GetLocator — exponentially-spaced hash list for P2P sync."""
        if index is None:
            index = self.tip()
        hashes = []
        step = 1
        while index is not None:
            hashes.append(index.hash)
            if index.height == 0:
                break
            h = max(index.height - step, 0)
            if index in self:
                index = self[h]
            else:
                index = index.get_ancestor(h)
            if len(hashes) > 10:
                step *= 2
        return hashes
