"""UTXO set model.

Reference: src/coins.{h,cpp} (Coin, CCoinsView, CCoinsViewBacked,
CCoinsViewCache), src/undo.h (CTxUndo/CBlockUndo). The layering is the same
as the reference's: persistent store <- in-memory cache <- per-operation
edits, with a batched flush. The persistent side is store/chainstate.py
(sqlite standing in for LevelDB — SURVEY.md §8.5.6 documents the deviation).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

from ..consensus.serialize import (
    ByteReader,
    deser_compact_size,
    deser_var_bytes,
    ser_compact_size,
    ser_var_bytes,
)
from ..consensus.tx import COutPoint, CTransaction, CTxOut


@dataclass(frozen=True)
class Coin:
    """An unspent output: CTxOut + metadata (src/coins.h:~30 (Coin)).
    height carries the creating block's height; coinbase outputs are
    spendable only after COINBASE_MATURITY confirmations."""

    out: CTxOut
    height: int
    is_coinbase: bool

    def serialize(self) -> bytes:
        code = self.height * 2 + (1 if self.is_coinbase else 0)
        return (
            ser_compact_size(code)
            + ser_compact_size(self.out.value)
            + ser_var_bytes(self.out.script_pubkey)
        )

    @classmethod
    def deserialize(cls, b: bytes) -> "Coin":
        r = ByteReader(b)
        code = deser_compact_size(r, range_check=False)
        value = deser_compact_size(r, range_check=False)
        script = deser_var_bytes(r)
        return cls(CTxOut(value, script), code // 2, bool(code & 1))


class CoinsView:
    """Abstract view of the UTXO set — CCoinsView (src/coins.h:~150)."""

    def get_coin(self, outpoint: COutPoint) -> Optional[Coin]:
        raise NotImplementedError

    def have_coin(self, outpoint: COutPoint) -> bool:
        return self.get_coin(outpoint) is not None

    def best_block(self) -> bytes:
        raise NotImplementedError

    def batch_write(self, coins: dict, best_block: bytes) -> None:
        raise NotImplementedError


class MemoryCoinsView(CoinsView):
    """Dict-backed bottom view (tests + regtest-in-memory operation)."""

    def __init__(self):
        self._coins: dict[COutPoint, Coin] = {}
        self._best = b"\x00" * 32

    def get_coin(self, outpoint):
        return self._coins.get(outpoint)

    def best_block(self) -> bytes:
        return self._best

    def batch_write(self, coins, best_block):
        for op, coin in coins.items():
            if coin is None:
                self._coins.pop(op, None)
            else:
                self._coins[op] = coin
        self._best = best_block

    def __len__(self):
        return len(self._coins)

    def all_coins(self) -> Iterator[tuple[COutPoint, Coin]]:
        return iter(self._coins.items())


class CoinsCache(CoinsView):
    """Write-back cache over a backing view — CCoinsViewCache
    (src/coins.h:~200). Entries: present Coin = live; None = spent/deleted
    (tombstone to push down on flush); absent = not yet fetched."""

    def __init__(self, base: CoinsView):
        self.base = base
        self.cache: dict[COutPoint, Optional[Coin]] = {}
        self._dirty: set[COutPoint] = set()  # CCoinsCacheEntry::DIRTY
        self._best: Optional[bytes] = None

    # -- reads --

    def get_coin(self, outpoint):
        if outpoint in self.cache:
            return self.cache[outpoint]
        coin = self.base.get_coin(outpoint)
        if coin is not None:
            self.cache[outpoint] = coin  # clean read-through entry
        return coin

    def have_coin_in_cache(self, outpoint) -> bool:
        return self.cache.get(outpoint) is not None

    def have_coin(self, outpoint) -> bool:
        """HaveCoin without materializing: a cache-resident entry (live or
        tombstone) answers immediately; otherwise the base is asked for
        EXISTENCE only — no Coin deserialization, no read-through entry
        polluting this layer (the BIP30 scan probes every output of every
        tx, and caching those misses-by-construction would bloat the
        -dbcache working set for nothing)."""
        if outpoint in self.cache:
            return self.cache[outpoint] is not None
        return self.base.have_coin(outpoint)

    def have_coin_cached(self, outpoint) -> Optional[bool]:
        """Resolve have_coin from in-memory cache layers ALONE: True/False
        when some layer holds the entry (live or tombstone), None when the
        bottom store would have to be consulted. The BIP30 fast path uses
        this to count store probes actually saved."""
        if outpoint in self.cache:
            return self.cache[outpoint] is not None
        probe = getattr(self.base, "have_coin_cached", None)
        return probe(outpoint) if probe is not None else None

    def best_block(self) -> bytes:
        if self._best is None:
            self._best = self.base.best_block()
        return self._best

    def set_best_block(self, h: bytes) -> None:
        self._best = h

    # -- writes --

    def add_coin(self, outpoint: COutPoint, coin: Coin, overwrite: bool = False):
        """AddCoin (src/coins.cpp:~50). Refuses silent overwrite of an
        unspent coin unless overwrite (the BIP30 special-case plumbing)."""
        if not overwrite and self.cache.get(outpoint) is not None:
            raise ValueError(f"coin already present: {outpoint!r}")
        self.cache[outpoint] = coin
        self._dirty.add(outpoint)

    def spend_coin(self, outpoint: COutPoint) -> Optional[Coin]:
        """SpendCoin: returns the spent coin (for undo data), tombstones it."""
        coin = self.get_coin(outpoint)
        if coin is None:
            return None
        self.cache[outpoint] = None
        self._dirty.add(outpoint)
        return coin

    def batch_write(self, coins: dict, best_block: bytes) -> None:
        """Absorb a child cache layer's (dirty) edits —
        CCoinsViewCache::BatchWrite. Tombstones stay tombstones until the
        bottom store sees them."""
        for op, coin in coins.items():
            self.cache[op] = coin
            self._dirty.add(op)
        self._best = best_block

    def flush(self) -> None:
        """Push DIRTY edits to the base in one batch — CCoinsViewCache::Flush.
        Clean read-through entries are dropped, not written (the reference's
        DIRTY-flag behavior: flush cost scales with modifications, not with
        the read set). The batch plus best-block marker is the crash-safety
        unit (SURVEY.md §6.3)."""
        self.base.batch_write(
            {op: self.cache[op] for op in self._dirty}, self.best_block()
        )
        self.cache.clear()
        self._dirty.clear()

    def cache_size(self) -> int:
        return len(self.cache)

    def estimated_bytes(self) -> int:
        """DynamicMemoryUsage analogue (coins.cpp): rough per-entry cost of
        the Python dict entry + COutPoint + Coin (~250 bytes measured with
        sys.getsizeof over the populated structures). Drives the -dbcache
        flush threshold, so it needs to be proportional, not exact."""
        return len(self.cache) * 250


def add_coins(view: CoinsCache, tx: CTransaction, height: int, overwrite: bool = False):
    """AddCoins (src/coins.cpp:~70): create outputs of tx at height."""
    cb = tx.is_coinbase()
    txid = tx.txid
    for i, out in enumerate(tx.vout):
        view.add_coin(COutPoint(txid, i), Coin(out, height, cb), overwrite or cb)


# ---- undo data (src/undo.h) ----

@dataclass
class TxUndo:
    """Spent coins of one transaction, input order — CTxUndo."""

    prevouts: list[Coin]

    def serialize(self) -> bytes:
        b = ser_compact_size(len(self.prevouts))
        for c in self.prevouts:
            s = c.serialize()
            b += ser_compact_size(len(s)) + s
        return b

    @classmethod
    def deserialize(cls, r: ByteReader) -> "TxUndo":
        n = deser_compact_size(r)
        prevouts = []
        for _ in range(n):
            ln = deser_compact_size(r)
            prevouts.append(Coin.deserialize(r.read_bytes(ln)))
        return cls(prevouts)


@dataclass
class BlockUndo:
    """Per-block undo data (rev?????.dat payload) — CBlockUndo. One TxUndo
    per non-coinbase transaction, block order."""

    vtxundo: list[TxUndo]

    def serialize(self) -> bytes:
        b = ser_compact_size(len(self.vtxundo))
        for u in self.vtxundo:
            b += u.serialize()
        return b

    @classmethod
    def from_bytes(cls, data: bytes) -> "BlockUndo":
        r = ByteReader(data)
        n = deser_compact_size(r)
        return cls([TxUndo.deserialize(r) for _ in range(n)])
