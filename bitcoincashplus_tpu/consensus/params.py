"""Chain parameters — main / testnet / regtest.

Reference: src/chainparams.cpp (CMainParams, CTestNetParams, CRegTestParams,
SelectParams), src/consensus/params.h (Consensus::Params),
src/chainparamsbase.cpp (ports/datadirs). Typed dataclasses replace the
string-keyed reference structs (SURVEY.md §6.6 decision) while preserving the
flag-compatible selection surface (-regtest/-testnet).

Genesis blocks are CONSTRUCTED here exactly as CreateGenesisBlock
(src/chainparams.cpp:~20) does and self-checked against the known mainnet
hash in tests — our strongest offline consensus anchor (SURVEY.md §8.5.3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

from .block import CBlock, CBlockHeader
from .merkle import compute_merkle_root
from .serialize import hex_to_hash
from .tx import COIN, COutPoint, CTransaction, CTxIn, CTxOut
from .versionbits import NO_TIMEOUT, VBDeployment


@dataclass(frozen=True)
class Consensus:
    """Consensus::Params (src/consensus/params.h)."""

    pow_limit: int
    pow_target_timespan: int = 14 * 24 * 60 * 60  # two weeks
    pow_target_spacing: int = 10 * 60
    pow_allow_min_difficulty_blocks: bool = False
    pow_no_retargeting: bool = False
    subsidy_halving_interval: int = 210_000
    coinbase_maturity: int = 100  # COINBASE_MATURITY (src/consensus/consensus.h)
    bip34_height: int = 0  # height-in-coinbase activation
    bip16_time: int = 1333238400  # P2SH switch time (nBIP16SwitchTime)
    bip65_height: int = -1  # CHECKLOCKTIMEVERIFY (-1 = never)
    bip66_height: int = -1  # strict DER
    csv_height: int = -1  # BIP68/112/113 CHECKSEQUENCEVERIFY bundle
    # BCH-family deltas [fork-delta, hedged — SURVEY.md §0]:
    uahf_height: int = -1  # SIGHASH_FORKID activation (-1 = never)
    use_cash_daa: bool = False
    # cw-144 DAA activation height (BCH Nov-2017 rules); below it the
    # EDA applies while use_cash_daa is set. -1 = EDA era forever.
    daa_height: int = -1
    # BIP9 versionbits (src/consensus/params.h nRuleChangeActivationThreshold
    # / nMinerConfirmationWindow / vDeployments) — see consensus/versionbits.py
    rule_change_activation_threshold: int = 1916  # 95% of 2016
    miner_confirmation_window: int = 2016
    deployments: tuple = ()

    @property
    def difficulty_adjustment_interval(self) -> int:
        return self.pow_target_timespan // self.pow_target_spacing


@dataclass(frozen=True)
class ChainParams:
    """CChainParams (src/chainparams.h)."""

    network: str
    consensus: Consensus
    genesis: CBlock
    # P2P wire netmagic (pchMessageStart) — fork-specific values would differ;
    # using the lineage defaults [fork-delta, hedged].
    netmagic: bytes = b"\xf9\xbe\xb4\xd9"
    default_port: int = 8333
    rpc_port: int = 8332
    # base58 version bytes (src/chainparams.cpp base58Prefixes)
    pubkey_addr_prefix: int = 0x00
    script_addr_prefix: int = 0x05
    secret_key_prefix: int = 0x80
    # checkpoint map height -> block hash (wire order) — checkpointData
    checkpoints: dict = field(default_factory=dict)
    # assumevalid: skip script checks at/below this block (defaultAssumeValid)
    assume_valid: bytes | None = None
    minimum_chain_work: int = 0
    require_standard: bool = True
    max_block_size: int = 1_000_000  # MAX_BLOCK_BASE_SIZE; BCH forks raise it
    max_block_sigops: int = 20_000

    @property
    def genesis_hash(self) -> bytes:
        return self.genesis.get_hash()


GENESIS_TIMESTAMP_TEXT = (
    b"The Times 03/Jan/2009 Chancellor on brink of second bailout for banks"
)
GENESIS_OUTPUT_PUBKEY = bytes.fromhex(
    "04678afdb0fe5548271967f1a67130b7105cd6a828e03909a67962e0ea1f61deb6"
    "49f6bc3f4cef38c4f35504e51ec112de5c384df7ba0b8d578a4c702b6bf11d5f"
)


def create_genesis_block(time: int, nonce: int, bits: int, version: int, reward: int) -> CBlock:
    """CreateGenesisBlock (src/chainparams.cpp:~20): coinbase scriptSig pushes
    (486604799, CScriptNum(4), timestamp text); output pays the Satoshi pubkey."""
    # scriptSig: push <04 bits LE-trimmed> = 0x04ffff001d, push 0x01 0x04, push text
    script_sig = (
        bytes([4]) + (486604799).to_bytes(4, "little")
        + bytes([1]) + bytes([4])
        + bytes([len(GENESIS_TIMESTAMP_TEXT)]) + GENESIS_TIMESTAMP_TEXT
    )
    script_pubkey = bytes([len(GENESIS_OUTPUT_PUBKEY)]) + GENESIS_OUTPUT_PUBKEY + b"\xac"  # OP_CHECKSIG
    coinbase = CTransaction(
        version=1,
        vin=(CTxIn(COutPoint(), script_sig, 0xFFFFFFFF),),
        vout=(CTxOut(reward, script_pubkey),),
        locktime=0,
    )
    root, _ = compute_merkle_root([coinbase.txid])
    header = CBlockHeader(
        version=version,
        hash_prev_block=b"\x00" * 32,
        hash_merkle_root=root,
        time=time,
        bits=bits,
        nonce=nonce,
    )
    return CBlock(header, (coinbase,))


@lru_cache(maxsize=None)
def main_params() -> ChainParams:
    """CMainParams (src/chainparams.cpp:~60)."""
    consensus = Consensus(
        pow_limit=0x00000000FFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF,
        bip34_height=227_931,
        bip65_height=388_381,  # v4 blocks (BIP65 deployment height)
        bip66_height=363_725,  # v3 blocks (BIP66)
        csv_height=419_328,  # CSV softfork activation
        uahf_height=478_559,  # [fork-delta, hedged] BCH-family split height
        use_cash_daa=False,  # per-run via -cashdaa/-daaheight (node/config)
        deployments=(
            # vDeployments[DEPLOYMENT_TESTDUMMY] (chainparams.cpp)
            VBDeployment("testdummy", 28, 1199145601, 1230767999),
            # DEPLOYMENT_CSV: the BIP9 run that activated at csv_height
            VBDeployment("csv", 0, 1462060800, 1493596800),
        ),
    )
    genesis = create_genesis_block(1231006505, 2083236893, 0x1D00FFFF, 1, 50 * COIN)
    return ChainParams(
        network="main",
        consensus=consensus,
        genesis=genesis,
        netmagic=b"\xf9\xbe\xb4\xd9",
        default_port=8333,
        rpc_port=8332,
        checkpoints={
            11_111: hex_to_hash("0000000069e244f73d78e8fd29ba2fd2ed618bd6fa2ee92559f542fdb26e7c1d"),
            105_000: hex_to_hash("00000000000291ce28027faea320c8d2b054b2e0fe44a773f3eefb151d6bdc97"),
            134_444: hex_to_hash("00000000000005b12ffd4cd315cd34ffd4a594f430ac814c91184a0d42d2b0fe"),
        },
        max_block_size=8_000_000,  # [fork-delta, hedged] big-block fork
    )


@lru_cache(maxsize=None)
def testnet_params() -> ChainParams:
    """CTestNetParams (src/chainparams.cpp:~180)."""
    consensus = Consensus(
        pow_limit=0x00000000FFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF,
        pow_allow_min_difficulty_blocks=True,
        bip34_height=21_111,
        bip65_height=581_885,
        bip66_height=330_776,
        csv_height=770_112,
    )
    genesis = create_genesis_block(1296688602, 414098458, 0x1D00FFFF, 1, 50 * COIN)
    return ChainParams(
        network="test",
        consensus=consensus,
        genesis=genesis,
        netmagic=b"\x0b\x11\x09\x07",
        default_port=18333,
        rpc_port=18332,
        pubkey_addr_prefix=0x6F,
        script_addr_prefix=0xC4,
        secret_key_prefix=0xEF,
        require_standard=False,
    )


@lru_cache(maxsize=None)
def regtest_params() -> ChainParams:
    """CRegTestParams (src/chainparams.cpp:~280) — the universal fake backend:
    trivially low difficulty so tests mine instantly (SURVEY.md §5.1)."""
    consensus = Consensus(
        pow_limit=0x7FFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF,
        pow_allow_min_difficulty_blocks=True,
        pow_no_retargeting=True,
        subsidy_halving_interval=150,
        bip34_height=0,
        bip16_time=0,  # P2SH always on (regtest, like the reference)
        bip65_height=0,
        bip66_height=0,
        csv_height=0,
        uahf_height=0,
        rule_change_activation_threshold=108,  # 75% of 144 (regtest)
        miner_confirmation_window=144,
        deployments=(
            VBDeployment("testdummy", 28, 0, NO_TIMEOUT),
        ),
    )
    genesis = create_genesis_block(1296688602, 2, 0x207FFFFF, 1, 50 * COIN)
    return ChainParams(
        network="regtest",
        consensus=consensus,
        genesis=genesis,
        netmagic=b"\xfa\xbf\xb5\xda",
        default_port=18444,
        rpc_port=18443,
        pubkey_addr_prefix=0x6F,
        script_addr_prefix=0xC4,
        secret_key_prefix=0xEF,
        require_standard=False,
    )


_NETWORKS = {
    "main": main_params,
    "test": testnet_params,
    "testnet": testnet_params,
    "regtest": regtest_params,
}


def select_params(network: str) -> ChainParams:
    """SelectParams (src/chainparams.cpp:~330)."""
    try:
        return _NETWORKS[network]()
    except KeyError:
        raise ValueError(f"unknown network {network!r}") from None


def get_block_subsidy(height: int, consensus: Consensus) -> int:
    """GetBlockSubsidy (src/validation.cpp:~1160): 50-coin base, halving every
    subsidy_halving_interval, zero after 64 halvings."""
    halvings = height // consensus.subsidy_halving_interval
    if halvings >= 64:
        return 0
    return (50 * COIN) >> halvings
