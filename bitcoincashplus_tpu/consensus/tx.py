"""Transaction primitives.

Reference: src/primitives/transaction.{h,cpp} (COutPoint, CTxIn, CTxOut,
CTransaction, CTransaction::ComputeHash). Wire format byte-identical; txid =
SHA256d(serialized tx). The BCH-lineage fork has no segwit, so there is a
single serialization (no wtxid distinction) [fork-delta, hedged — SURVEY.md §0].

Immutable-after-construction like the reference's CTransaction (which is
const); use TxBuilder-style mutation then freeze via CTransaction.from_parts.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto.hashes import sha256d
from .serialize import (
    ByteReader,
    deser_i32,
    deser_i64,
    deser_u32,
    deser_var_bytes,
    deser_vector,
    hash_to_hex,
    ser_i32,
    ser_i64,
    ser_u32,
    ser_var_bytes,
    ser_vector,
)

COIN = 100_000_000  # satoshis per coin (src/amount.h COIN)
MAX_MONEY = 21_000_000 * COIN  # src/amount.h (MAX_MONEY)

SEQUENCE_FINAL = 0xFFFFFFFF
# nSequence locktime flags (src/primitives/transaction.h ~CTxIn)
SEQUENCE_LOCKTIME_DISABLE_FLAG = 1 << 31
SEQUENCE_LOCKTIME_TYPE_FLAG = 1 << 22
SEQUENCE_LOCKTIME_MASK = 0x0000FFFF

LOCKTIME_THRESHOLD = 500_000_000  # below: block height, above: unix time


def money_range(v: int) -> bool:
    return 0 <= v <= MAX_MONEY


@dataclass(frozen=True)
class COutPoint:
    """(txid, vout index) — src/primitives/transaction.h (COutPoint)."""

    hash: bytes = b"\x00" * 32  # txid in wire order
    n: int = 0xFFFFFFFF

    def serialize(self) -> bytes:
        return self.hash + ser_u32(self.n)

    @classmethod
    def deserialize(cls, r: ByteReader) -> "COutPoint":
        h = r.read_bytes(32)
        return cls(h, deser_u32(r))

    def is_null(self) -> bool:
        return self.hash == b"\x00" * 32 and self.n == 0xFFFFFFFF

    def __repr__(self) -> str:
        return f"COutPoint({bytes(reversed(self.hash)).hex()[:16]}…,{self.n})"


@dataclass(frozen=True)
class CTxIn:
    prevout: COutPoint = field(default_factory=COutPoint)
    script_sig: bytes = b""
    sequence: int = SEQUENCE_FINAL

    def serialize(self) -> bytes:
        return (
            self.prevout.serialize()
            + ser_var_bytes(self.script_sig)
            + ser_u32(self.sequence)
        )

    @classmethod
    def deserialize(cls, r: ByteReader) -> "CTxIn":
        prevout = COutPoint.deserialize(r)
        script_sig = deser_var_bytes(r)
        return cls(prevout, script_sig, deser_u32(r))


@dataclass(frozen=True)
class CTxOut:
    value: int = -1  # satoshis
    script_pubkey: bytes = b""

    def serialize(self) -> bytes:
        return ser_i64(self.value) + ser_var_bytes(self.script_pubkey)

    @classmethod
    def deserialize(cls, r: ByteReader) -> "CTxOut":
        value = deser_i64(r)
        return cls(value, deser_var_bytes(r))

    def is_null(self) -> bool:
        return self.value == -1


class CTransaction:
    """Immutable transaction; hash computed once at construction
    (src/primitives/transaction.cpp CTransaction::ComputeHash)."""

    __slots__ = ("version", "vin", "vout", "locktime", "_ser", "_txid")

    CURRENT_VERSION = 2

    def __init__(
        self,
        version: int = CURRENT_VERSION,
        vin: tuple[CTxIn, ...] = (),
        vout: tuple[CTxOut, ...] = (),
        locktime: int = 0,
    ):
        self.version = version
        self.vin = tuple(vin)
        self.vout = tuple(vout)
        self.locktime = locktime
        self._ser = self._serialize()
        self._txid = sha256d(self._ser)

    def _serialize(self) -> bytes:
        return (
            ser_i32(self.version)
            + ser_vector(self.vin, CTxIn.serialize)
            + ser_vector(self.vout, CTxOut.serialize)
            + ser_u32(self.locktime)
        )

    def serialize(self) -> bytes:
        return self._ser

    @classmethod
    def deserialize(cls, r: ByteReader) -> "CTransaction":
        version = deser_i32(r)
        vin = deser_vector(r, CTxIn.deserialize)
        vout = deser_vector(r, CTxOut.deserialize)
        locktime = deser_u32(r)
        return cls(version, tuple(vin), tuple(vout), locktime)

    @classmethod
    def from_bytes(cls, b: bytes) -> "CTransaction":
        r = ByteReader(b)
        tx = cls.deserialize(r)
        if not r.empty():
            from .serialize import DeserializationError

            raise DeserializationError("trailing bytes after transaction")
        return tx

    @property
    def txid(self) -> bytes:
        """SHA256d of serialization, wire order."""
        return self._txid

    @property
    def txid_hex(self) -> str:
        return hash_to_hex(self._txid)

    def is_coinbase(self) -> bool:
        return len(self.vin) == 1 and self.vin[0].prevout.is_null()

    def total_output_value(self) -> int:
        return sum(o.value for o in self.vout)

    def size(self) -> int:
        return len(self._ser)

    def __eq__(self, other) -> bool:
        return isinstance(other, CTransaction) and self._txid == other._txid

    def __hash__(self) -> int:
        return int.from_bytes(self._txid[:8], "little")

    def __repr__(self) -> str:
        return f"CTransaction({self.txid_hex[:16]}…, {len(self.vin)} in, {len(self.vout)} out)"
