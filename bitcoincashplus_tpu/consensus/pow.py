"""Proof-of-work rules.

Reference: src/pow.cpp:~13 (GetNextWorkRequired), :~50
(CalculateNextWorkRequired), :~74 (CheckProofOfWork);
src/arith_uint256.cpp:~190 (arith_uint256::SetCompact / GetCompact).

Python ints replace arith_uint256 (exact 256-bit arithmetic is native here —
no limb code needed on the host; the on-chip target compare in the miner
kernel uses 8×u32 limbs, see ops/sha256.py).

The BCH-family lineage adds EDA / cw-144 DAA difficulty rules
[fork-delta, hedged — SURVEY.md §0]; those are gated behind
Consensus params flags and implemented as ``get_next_work_required_cash``.
"""

from __future__ import annotations

# ---- compact bits ("nBits") codec — arith_uint256::SetCompact/GetCompact ----

def compact_to_target(bits: int) -> tuple[int, bool]:
    """Decode compact bits to a 256-bit target.

    Returns (target, overflow_or_negative). Mirrors SetCompact's fNegative /
    fOverflow outputs: consensus treats negative, zero, or overflowing targets
    as invalid PoW.
    """
    size = bits >> 24
    word = bits & 0x007FFFFF
    if size <= 3:
        target = word >> (8 * (3 - size))
    else:
        target = word << (8 * (size - 3))
    negative = word != 0 and (bits & 0x00800000) != 0
    overflow = word != 0 and (
        size > 34 or (word > 0xFF and size > 33) or (word > 0xFFFF and size > 32)
    )
    return target, (negative or overflow)


def target_to_compact(target: int) -> int:
    """Encode a 256-bit target as compact bits — arith_uint256::GetCompact."""
    if target == 0:
        return 0
    size = (target.bit_length() + 7) // 8
    if size <= 3:
        word = target << (8 * (3 - size))
    else:
        word = target >> (8 * (size - 3))
    # Avoid setting the sign bit: shift mantissa right, bump exponent.
    if word & 0x00800000:
        word >>= 8
        size += 1
    return (size << 24) | word


def check_proof_of_work(block_hash: bytes, bits: int, params) -> bool:
    """CheckProofOfWork (src/pow.cpp:~74): hash (as LE uint256) <= target,
    target in (0, pow_limit]."""
    target, bad = compact_to_target(bits)
    if bad or target == 0 or target > params.pow_limit:
        return False
    return int.from_bytes(block_hash, "little") <= target


def check_headers_pow_batch(headers80: list, params) -> list[bool]:
    """Batched CheckProofOfWork over serialized 80-byte headers — the
    headers-first sync pre-filter (p2p/connman._msg_headers): one
    supervised device dispatch hashes the whole announcement batch
    (ops/sha256.sha256d_headers rides the sha256 circuit breaker, so a
    dead backend degrades to per-header host hashing), then each digest is
    compared to its own header's decoded target on host. Verdicts are
    bit-identical to per-header check_proof_of_work by construction:
    target decoding and the <= compare are this module's scalar code."""
    import numpy as np

    from ..ops.sha256 import sha256d_headers

    if not headers80:
        return []
    arr = np.frombuffer(b"".join(headers80), dtype=np.uint8).reshape(-1, 80)
    n = arr.shape[0]
    # pad to a pow2 bucket (min 16) so the jit compiles O(log n) distinct
    # shapes across all announcement sizes, not one per batch length
    bucket = max(16, 1 << (n - 1).bit_length())
    if bucket != n:
        arr = np.concatenate([arr, np.repeat(arr[:1], bucket - n, axis=0)])
    digests = sha256d_headers(arr)
    from ..crypto.hashes import sha256d

    out = []
    for i, raw in enumerate(headers80):
        bits = int.from_bytes(raw[72:76], "little")
        target, bad = compact_to_target(bits)
        ok = (
            not bad and 0 < target <= params.pow_limit
            and int.from_bytes(digests[i].tobytes(), "little") <= target
        )
        if not ok and not bad and 0 < target <= params.pow_limit:
            # every FAILING verdict is host-confirmed before it is
            # returned: the batch's lane-0 spot check can miss a single
            # corrupted device lane, and callers punish peers on a False
            # here — a lying device must not be able to stall headers
            # sync by framing honest announcements (cheap: honest
            # traffic almost never takes this branch)
            ok = int.from_bytes(sha256d(raw), "little") <= target
        out.append(ok)
    return out


def get_block_proof(bits: int) -> int:
    """Chain-work contribution of a block — GetBlockProof
    (src/chain.cpp:~120): floor(2^256 / (target+1))."""
    target, bad = compact_to_target(bits)
    if bad or target == 0:
        return 0
    return (1 << 256) // (target + 1)


# ---- difficulty adjustment ----

def get_next_work_required(prev_index, new_block_time: int, params) -> int:
    """GetNextWorkRequired (src/pow.cpp:~13) — Core-lineage 2016-block rule.

    prev_index is the CBlockIndex of the tip the new block builds on (None at
    genesis). Testnet min-difficulty and regtest no-retarget behaviors match
    the reference.
    """
    pow_limit_bits = target_to_compact(params.pow_limit)
    if prev_index is None:
        return pow_limit_bits
    # NB: fPowNoRetargeting is honored inside CalculateNextWorkRequired (as in
    # the reference) so the min-difficulty special cases below still apply on
    # regtest/testnet chains.

    height = prev_index.height + 1
    # BCH-lineage routing [fork-delta, hedged]: with use_cash_daa set,
    # cw-144 DAA from daa_height and the EDA overlay before it. The
    # cash rules deliberately do NOT short-circuit on pow_no_retargeting:
    # -cashdaa on regtest is the fork-storm harness knob and must run the
    # same rule code every node will agree on (on a min-difficulty chain
    # both rules clamp at/near pow_limit, so mining stays trivial).
    if params.use_cash_daa and height >= params.daa_height >= 0:
        return get_next_work_required_cash(prev_index, new_block_time, params)
    interval = params.difficulty_adjustment_interval
    if height % interval != 0:
        if params.use_cash_daa:
            # EDA era (BCH-lineage): on min-difficulty chains the
            # 20-minute exception answers first, and otherwise the rule
            # anchors on the last REAL-difficulty block (the same
            # walk-back as the Core branch below — without it one
            # min-difficulty block would floor the whole interval at
            # pow_limit, diverging from reference nodes); then the
            # 12h-MTP-gap emergency adjustment, which clamps at
            # pow_limit so all-min chains keep their bits while still
            # RUNNING the rule every node must agree on
            anchor = prev_index
            if params.pow_allow_min_difficulty_blocks:
                if (new_block_time
                        > prev_index.time + params.pow_target_spacing * 2):
                    return pow_limit_bits
                while (anchor.prev is not None
                       and anchor.height % interval != 0
                       and anchor.bits == pow_limit_bits):
                    anchor = anchor.prev
            return eda_bits(anchor, params)
        if params.pow_allow_min_difficulty_blocks:
            # Testnet special-case: 20-minute gap → min difficulty; otherwise
            # walk back to the last non-min-difficulty block.
            if new_block_time > prev_index.time + params.pow_target_spacing * 2:
                return pow_limit_bits
            idx = prev_index
            while (
                idx.prev is not None
                and idx.height % interval != 0
                and idx.bits == pow_limit_bits
            ):
                idx = idx.prev
            return idx.bits
        return prev_index.bits

    # Retarget height. fPowNoRetargeting short-circuits in the reference's
    # CalculateNextWorkRequired before first_block_time is used; checking it
    # here avoids the (irrelevant) 2016-ancestor walk.
    if params.pow_no_retargeting:
        return prev_index.bits
    first = prev_index.get_ancestor(height - interval)
    assert first is not None
    return calculate_next_work_required(prev_index, first.time, params)


def calculate_next_work_required(prev_index, first_block_time: int, params) -> int:
    """CalculateNextWorkRequired (src/pow.cpp:~50) with the reference's
    4x clamp and integer order of operations."""
    if params.pow_no_retargeting:
        return prev_index.bits

    timespan = prev_index.time - first_block_time
    min_ts = params.pow_target_timespan // 4
    max_ts = params.pow_target_timespan * 4
    timespan = max(min_ts, min(max_ts, timespan))

    target, _ = compact_to_target(prev_index.bits)
    # Reference order: bnNew *= nActualTimespan; bnNew /= nPowTargetTimespan
    target = target * timespan // params.pow_target_timespan
    if target > params.pow_limit:
        target = params.pow_limit
    return target_to_compact(target)


# ---- BCH-family difficulty [fork-delta, hedged] ----

def eda_bits(prev_index, params) -> int:
    """Emergency Difficulty Adjustment (BCH-lineage pow.cpp, the Aug-2017
    pre-DAA rule): on a non-retarget height, if the median-time-past gap
    across the last six blocks exceeds 12 hours, the target grows by 25%
    (difficulty drops 20%), clamped at pow_limit. Otherwise the previous
    bits carry forward. Only reachable when params.use_cash_daa and the
    height is below daa_height."""
    if prev_index.height < 6:
        return prev_index.bits
    anc = prev_index.get_ancestor(prev_index.height - 6)
    if anc is None:
        return prev_index.bits
    mtp_gap = prev_index.get_median_time_past() - anc.get_median_time_past()
    if mtp_gap <= 12 * 3600:
        return prev_index.bits
    target, _ = compact_to_target(prev_index.bits)
    target += target >> 2  # +25% target = -20% difficulty
    if target > params.pow_limit:
        target = params.pow_limit
    return target_to_compact(target)


def get_next_work_required_cash(prev_index, new_block_time: int, params) -> int:
    """cw-144 DAA (simplified median-past form) used by BCH-family forks after
    their DAA activation height; EDA before it. Only active when
    params.use_cash_daa — OFF for the Bitcoin-compatible default chains so the
    mainnet genesis/retarget tests stay exact. [fork-delta, hedged]
    """
    pow_limit_bits = target_to_compact(params.pow_limit)
    if prev_index is None or prev_index.height < 144 + 2:
        return pow_limit_bits if prev_index is None else prev_index.bits

    def suitable(idx):
        # median-of-three by timestamp — exact GetSuitableBlock sorting
        # network (BCH-lineage pow.cpp); tie-handling must match, so no
        # stable sort here.
        b = [idx.prev.prev, idx.prev, idx]
        if b[0].time > b[2].time:
            b[0], b[2] = b[2], b[0]
        if b[0].time > b[1].time:
            b[0], b[1] = b[1], b[0]
        if b[1].time > b[2].time:
            b[1], b[2] = b[2], b[1]
        return b[1]

    last = suitable(prev_index)
    first = suitable(prev_index.get_ancestor(prev_index.height - 144))
    timespan = last.time - first.time
    timespan = max(72 * params.pow_target_spacing, min(288 * params.pow_target_spacing, timespan))

    work = last.chain_work - first.chain_work
    work = work * params.pow_target_spacing // timespan
    if work == 0:
        return pow_limit_bits
    target = (1 << 256) // work - 1
    if target > params.pow_limit:
        target = params.pow_limit
    return target_to_compact(target)
