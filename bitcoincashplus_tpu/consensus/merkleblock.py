"""Partial merkle trees + filtered blocks.

Reference: src/merkleblock.{h,cpp} (CPartialMerkleTree, CMerkleBlock).
A partial merkle tree proves a subset of a block's txids against its
merkle root with ~32·log(n) bytes: a depth-first traversal emitting one
flag bit per visited node and a hash for every pruned subtree (and every
matched leaf). Serves `merkleblock` P2P responses to BIP37 peers and the
gettxoutproof/verifytxoutproof RPCs.
"""

from __future__ import annotations

import struct
from typing import Optional

from ..crypto.hashes import sha256d
from .serialize import (
    ByteReader,
    deser_compact_size,
    ser_compact_size,
)

# cap nTransactions like the reference: a block can't carry more txs than
# size/60 (minimal tx size); used to reject absurd proofs before allocating
MAX_BLOCK_SIZE = 8_000_000
MIN_TX_SIZE = 60


class CPartialMerkleTree:
    def __init__(self, n_transactions: int = 0,
                 bits: Optional[list[bool]] = None,
                 hashes: Optional[list[bytes]] = None):
        self.n_transactions = n_transactions
        self.bits: list[bool] = bits or []
        self.hashes: list[bytes] = hashes or []
        self.bad = False

    # -- construction (CPartialMerkleTree::CPartialMerkleTree) ----------

    @classmethod
    def from_txids(cls, txids: list[bytes],
                   matches: list[bool]) -> "CPartialMerkleTree":
        self = cls(len(txids))
        height = 0
        while self._calc_tree_width(height) > 1:
            height += 1
        self._traverse_and_build(height, 0, txids, matches)
        return self

    def _calc_tree_width(self, height: int) -> int:
        return (self.n_transactions + (1 << height) - 1) >> height

    def _calc_hash(self, height: int, pos: int, txids: list[bytes]) -> bytes:
        if height == 0:
            return txids[pos]
        left = self._calc_hash(height - 1, pos * 2, txids)
        if pos * 2 + 1 < self._calc_tree_width(height - 1):
            right = self._calc_hash(height - 1, pos * 2 + 1, txids)
        else:
            right = left
        return sha256d(left + right)

    def _traverse_and_build(self, height: int, pos: int,
                            txids: list[bytes], matches: list[bool]) -> None:
        parent_of_match = False
        p = pos << height
        while p < (pos + 1) << height and p < self.n_transactions:
            parent_of_match |= matches[p]
            p += 1
        self.bits.append(parent_of_match)
        if height == 0 or not parent_of_match:
            self.hashes.append(self._calc_hash(height, pos, txids))
        else:
            self._traverse_and_build(height - 1, pos * 2, txids, matches)
            if pos * 2 + 1 < self._calc_tree_width(height - 1):
                self._traverse_and_build(height - 1, pos * 2 + 1, txids,
                                         matches)

    # -- verification (ExtractMatches) -----------------------------------

    def _traverse_and_extract(self, height: int, pos: int, cursor: list[int],
                              matched: list[tuple[int, bytes]]) -> bytes:
        bits_used, hashes_used = cursor
        if bits_used >= len(self.bits):
            self.bad = True
            return b"\x00" * 32
        parent_of_match = self.bits[bits_used]
        cursor[0] += 1
        if height == 0 or not parent_of_match:
            if cursor[1] >= len(self.hashes):
                self.bad = True
                return b"\x00" * 32
            h = self.hashes[cursor[1]]
            cursor[1] += 1
            if height == 0 and parent_of_match:
                matched.append((pos, h))
            return h
        left = self._traverse_and_extract(height - 1, pos * 2, cursor, matched)
        if pos * 2 + 1 < self._calc_tree_width(height - 1):
            right = self._traverse_and_extract(height - 1, pos * 2 + 1,
                                               cursor, matched)
            if right == left:
                # identical left/right is the CVE-2012-2459 mutation shape
                self.bad = True
        else:
            right = left
        return sha256d(left + right)

    def extract_matches(self) -> Optional[tuple[bytes, list[tuple[int, bytes]]]]:
        """Returns (merkle_root, [(position, txid), ...]) or None if the
        proof is malformed (all the reference's rejection conditions)."""
        self.bad = False
        if self.n_transactions == 0:
            return None
        if self.n_transactions > MAX_BLOCK_SIZE // MIN_TX_SIZE:
            return None
        if len(self.hashes) > self.n_transactions:
            return None
        if len(self.bits) < len(self.hashes):
            return None
        height = 0
        while self._calc_tree_width(height) > 1:
            height += 1
        cursor = [0, 0]
        matched: list[tuple[int, bytes]] = []
        root = self._traverse_and_extract(height, 0, cursor, matched)
        if self.bad:
            return None
        # every bit and hash must be consumed (no trailing garbage)
        if (cursor[0] + 7) // 8 != (len(self.bits) + 7) // 8:
            return None
        if cursor[1] != len(self.hashes):
            return None
        return root, matched

    # -- serialization ---------------------------------------------------

    def serialize(self) -> bytes:
        out = [struct.pack("<I", self.n_transactions),
               ser_compact_size(len(self.hashes))]
        out.extend(self.hashes)
        packed = bytearray((len(self.bits) + 7) // 8)
        for i, bit in enumerate(self.bits):
            if bit:
                packed[i >> 3] |= 1 << (i & 7)
        out.append(ser_compact_size(len(packed)))
        out.append(bytes(packed))
        return b"".join(out)

    @classmethod
    def deserialize(cls, r: ByteReader) -> "CPartialMerkleTree":
        (n_tx,) = struct.unpack("<I", r.read_bytes(4))
        n_hashes = deser_compact_size(r)
        hashes = [r.read_bytes(32) for _ in range(n_hashes)]
        n_bytes = deser_compact_size(r)
        packed = r.read_bytes(n_bytes)
        bits = [bool(packed[i >> 3] & (1 << (i & 7)))
                for i in range(n_bytes * 8)]
        return cls(n_tx, bits, hashes)


class CMerkleBlock:
    """src/merkleblock.h CMerkleBlock: header + partial tree over the
    subset of txs selected by a bloom filter or explicit txid set."""

    def __init__(self, header, pmt: CPartialMerkleTree,
                 matched_txids: Optional[list[bytes]] = None):
        self.header = header
        self.pmt = pmt
        # convenience for the P2P path: which full txs to send after the
        # merkleblock message
        self.matched_txids = matched_txids or []

    @classmethod
    def from_block(cls, block, bloom_filter=None,
                   txid_set: Optional[set[bytes]] = None) -> "CMerkleBlock":
        txids = [tx.txid for tx in block.vtx]
        if bloom_filter is not None:
            matches = [bloom_filter.is_relevant_and_update(tx)
                       for tx in block.vtx]
        else:
            txid_set = txid_set or set()
            matches = [txid in txid_set for txid in txids]
        pmt = CPartialMerkleTree.from_txids(txids, matches)
        matched = [t for t, m in zip(txids, matches) if m]
        return cls(block.header, pmt, matched)

    def serialize(self) -> bytes:
        return self.header.serialize() + self.pmt.serialize()

    @classmethod
    def deserialize(cls, r: ByteReader) -> "CMerkleBlock":
        from .block import CBlockHeader

        header = CBlockHeader.deserialize(r)
        pmt = CPartialMerkleTree.deserialize(r)
        return cls(header, pmt)

    @classmethod
    def from_bytes(cls, b: bytes) -> "CMerkleBlock":
        return cls.deserialize(ByteReader(b))
