"""BIP9 versionbits deployment state machine.

Reference: src/versionbits.{h,cpp} (AbstractThresholdConditionChecker,
ThresholdState, VersionBitsState/ComputeBlockVersion) and the warning
plumbing in src/validation.cpp:~2200 (unknown-version upgrade warning).

The reference walks one MTP-gated period state machine per deployment:
DEFINED -> STARTED (start_time reached) -> LOCKED_IN (threshold of the
period signalled) -> ACTIVE, with STARTED -> FAILED on timeout. States are
a pure function of the period-boundary ancestor, memoized per boundary
block. The same machine here is a free function over CBlockIndex with an
explicit cache dict — no inheritance hierarchy; the per-deployment
`condition` is just the default bit test unless a caller overrides it.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Callable, Optional

VERSIONBITS_TOP_BITS = 0x20000000
VERSIONBITS_TOP_MASK = 0xE0000000
VERSIONBITS_NUM_BITS = 29

# start_time sentinels (consensus/params.h)
ALWAYS_ACTIVE = -1
NO_TIMEOUT = 1 << 62


class ThresholdState(Enum):
    DEFINED = "defined"
    STARTED = "started"
    LOCKED_IN = "locked_in"
    ACTIVE = "active"
    FAILED = "failed"


@dataclass(frozen=True)
class VBDeployment:
    """Consensus::BIP9Deployment (src/consensus/params.h)."""

    name: str
    bit: int
    start_time: int
    timeout: int


def default_condition(index, dep: VBDeployment) -> bool:
    """Condition(pindex): version signals TOP_BITS scheme + deployment bit."""
    v = index.header.version
    return (
        (v & VERSIONBITS_TOP_MASK) == VERSIONBITS_TOP_BITS
        and (v >> dep.bit) & 1 == 1
    )


def get_state_for(
    dep: VBDeployment,
    prev_index,  # CBlockIndex | None: block BEFORE the one being evaluated
    window: int,
    threshold: int,
    cache: Optional[dict] = None,
    condition: Callable = default_condition,
) -> ThresholdState:
    """AbstractThresholdConditionChecker::GetStateFor (versionbits.cpp:~10).

    State for the block AFTER prev_index. `cache` memoizes period-boundary
    states keyed by boundary block hash (VersionBitsCache entry)."""
    if dep.start_time == ALWAYS_ACTIVE:
        return ThresholdState.ACTIVE

    # walk prev back to the last period boundary (height % window == window-1)
    if prev_index is not None:
        prev_index = prev_index.get_ancestor(
            prev_index.height - ((prev_index.height + 1) % window)
        )

    # collect boundary ancestors until a cached/terminal state
    to_compute = []
    while prev_index is not None and (cache is None or prev_index.hash not in cache):
        if prev_index.get_median_time_past() < dep.start_time:
            # optimization from the reference: before start_time the state
            # is DEFINED; cache and stop walking
            if cache is not None:
                cache[prev_index.hash] = ThresholdState.DEFINED
            break
        to_compute.append(prev_index)
        prev_index = prev_index.get_ancestor(prev_index.height - window)

    if prev_index is None:
        state = ThresholdState.DEFINED
    elif cache is not None and prev_index.hash in cache:
        state = cache[prev_index.hash]
    else:
        state = ThresholdState.DEFINED  # the pre-start boundary found above

    # apply the state machine forward over the walked periods
    while to_compute:
        idx = to_compute.pop()
        if state == ThresholdState.DEFINED:
            if idx.get_median_time_past() >= dep.timeout:
                state = ThresholdState.FAILED
            elif idx.get_median_time_past() >= dep.start_time:
                state = ThresholdState.STARTED
        elif state == ThresholdState.STARTED:
            if idx.get_median_time_past() >= dep.timeout:
                state = ThresholdState.FAILED
            else:
                # count signalling blocks over the period ending at idx
                count = 0
                walk = idx
                for _ in range(window):
                    if walk is None:
                        break
                    if condition(walk, dep):
                        count += 1
                    walk = walk.prev
                if count >= threshold:
                    state = ThresholdState.LOCKED_IN
        elif state == ThresholdState.LOCKED_IN:
            state = ThresholdState.ACTIVE
        # ACTIVE and FAILED are terminal
        if cache is not None:
            cache[idx.hash] = state
    return state


def get_state_since_height(
    dep: VBDeployment, prev_index, window: int, threshold: int,
    cache: Optional[dict] = None,
) -> int:
    """GetStateSinceHeightFor: first height at which the current state
    applies (0 for DEFINED-from-genesis)."""
    state = get_state_for(dep, prev_index, window, threshold, cache)
    if state == ThresholdState.DEFINED:
        return 0
    # walk period boundaries backwards while the state is unchanged
    idx = prev_index
    if idx is not None:
        idx = idx.get_ancestor(idx.height - ((idx.height + 1) % window))
    while idx is not None:
        prev_boundary = idx.get_ancestor(idx.height - window)
        if get_state_for(dep, prev_boundary, window, threshold, cache) != state:
            break
        idx = prev_boundary
    return 0 if idx is None else idx.height + 1


class VersionBitsCache:
    """VersionBitsCache (versionbits.h): per-deployment boundary memo."""

    def __init__(self):
        self._per_dep: dict[str, dict] = {}

    def for_dep(self, dep: VBDeployment) -> dict:
        return self._per_dep.setdefault(dep.name, {})

    def clear(self):
        self._per_dep.clear()


def compute_block_version(prev_index, deployments, window: int,
                          threshold: int,
                          cache: Optional[VersionBitsCache] = None) -> int:
    """ComputeBlockVersion (src/miner.cpp:~60 / versionbits.cpp): TOP_BITS
    plus every deployment bit in STARTED or LOCKED_IN."""
    version = VERSIONBITS_TOP_BITS
    for dep in deployments:
        state = get_state_for(
            dep, prev_index, window, threshold,
            cache.for_dep(dep) if cache is not None else None,
        )
        if state in (ThresholdState.STARTED, ThresholdState.LOCKED_IN):
            version |= 1 << dep.bit
    return version


def unknown_version_signalling(tip, deployments, window: int) -> int:
    """The validation.cpp:~2200 upgrade warning: count of the last `window`
    blocks whose version uses the TOP_BITS scheme with bits outside every
    known deployment (a possible unknown soft fork signalling)."""
    known_mask = 0
    for dep in deployments:
        known_mask |= 1 << dep.bit
    count = 0
    idx = tip
    for _ in range(min(window, 100)):
        if idx is None:
            break
        v = idx.header.version
        if (
            (v & VERSIONBITS_TOP_MASK) == VERSIONBITS_TOP_BITS
            and v & ~(VERSIONBITS_TOP_MASK | known_mask) & ((1 << VERSIONBITS_NUM_BITS) - 1)
        ):
            count += 1
        idx = idx.prev
    return count
