"""Merkle tree construction (CPU reference path).

Reference: src/consensus/merkle.cpp:~45 (ComputeMerkleRoot), :~70
(BlockMerkleRoot). Consensus rule: at each level an odd node count duplicates
the last node. That duplication enables the CVE-2012-2459 mutation (a block
whose tx list ends in a duplicated pair hashes to the same root) — the
`mutated` out-flag detects identical adjacent nodes exactly like the
reference's comment block describes.

The TPU tree-reduction kernel (ops/merkle.py) is differential-tested
against this implementation.
"""

from __future__ import annotations

from ..crypto.hashes import sha256d


def compute_merkle_root(hashes: list[bytes]) -> tuple[bytes, bool]:
    """Returns (root, mutated). Empty list → zero hash like the reference."""
    if not hashes:
        return b"\x00" * 32, False
    mutated = False
    level = list(hashes)
    while len(level) > 1:
        # Mutation check runs BEFORE odd-padding: identical adjacent nodes at
        # even positions signal a CVE-2012-2459 style duplication (the padded
        # last pair is legitimately equal and must not flag).
        for i in range(0, len(level) - 1, 2):
            if level[i] == level[i + 1]:
                mutated = True
        if len(level) & 1:
            level.append(level[-1])
        level = [sha256d(level[i] + level[i + 1]) for i in range(0, len(level), 2)]
    return level[0], mutated


def block_merkle_root(block) -> tuple[bytes, bool]:
    """BlockMerkleRoot — root over txids (src/consensus/merkle.cpp:~70)."""
    return compute_merkle_root([tx.txid for tx in block.vtx])


def merkle_root_naive(hashes: list[bytes]) -> bytes:
    """Independent recursive recomputation for tests (mirrors the reference's
    merkle_tests.cpp strategy of checking against an older algorithm)."""
    if not hashes:
        return b"\x00" * 32
    if len(hashes) == 1:
        return hashes[0]
    if len(hashes) & 1:
        hashes = hashes + [hashes[-1]]
    return merkle_root_naive(
        [sha256d(hashes[i] + hashes[i + 1]) for i in range(0, len(hashes), 2)]
    )
