"""Block primitives.

Reference: src/primitives/block.{h,cpp} (CBlockHeader, CBlock,
CBlockHeader::GetHash at src/primitives/block.cpp:~13). The 80-byte header
layout is the kernel-critical structure for the TPU nonce sweep:

    bytes  0..3   nVersion        (i32 LE)
    bytes  4..35  hashPrevBlock   (32B wire order)
    bytes 36..67  hashMerkleRoot  (32B wire order)
    bytes 68..71  nTime           (u32 LE)
    bytes 72..75  nBits           (u32 LE)
    bytes 76..79  nNonce          (u32 LE)   <- inside SHA-256 message block 1

Bytes 0..63 are constant across a nonce sweep → midstate precompute
(SURVEY.md §4.5; crypto/hashes.py header_midstate).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..crypto.hashes import sha256d
from .serialize import (
    ByteReader,
    DeserializationError,
    deser_i32,
    deser_u32,
    deser_vector,
    hash_to_hex,
    ser_i32,
    ser_u32,
    ser_vector,
)
from .tx import CTransaction

HEADER_SIZE = 80
NONCE_OFFSET = 76


@dataclass(frozen=True)
class CBlockHeader:
    version: int = 0
    hash_prev_block: bytes = b"\x00" * 32
    hash_merkle_root: bytes = b"\x00" * 32
    time: int = 0
    bits: int = 0
    nonce: int = 0

    def serialize(self) -> bytes:
        return (
            ser_i32(self.version)
            + self.hash_prev_block
            + self.hash_merkle_root
            + ser_u32(self.time)
            + ser_u32(self.bits)
            + ser_u32(self.nonce)
        )

    @classmethod
    def deserialize(cls, r: ByteReader) -> "CBlockHeader":
        return cls(
            version=deser_i32(r),
            hash_prev_block=r.read_bytes(32),
            hash_merkle_root=r.read_bytes(32),
            time=deser_u32(r),
            bits=deser_u32(r),
            nonce=deser_u32(r),
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "CBlockHeader":
        if len(b) != HEADER_SIZE:
            raise DeserializationError("header must be 80 bytes")
        return cls.deserialize(ByteReader(b))

    def get_hash(self) -> bytes:
        """SHA256d of the 80-byte serialization — CBlockHeader::GetHash."""
        return sha256d(self.serialize())

    @property
    def hash_hex(self) -> str:
        return hash_to_hex(self.get_hash())

    def with_nonce(self, nonce: int) -> "CBlockHeader":
        return replace(self, nonce=nonce)


@dataclass(frozen=True)
class CBlock:
    header: CBlockHeader
    vtx: tuple[CTransaction, ...] = ()

    def serialize(self) -> bytes:
        return self.header.serialize() + ser_vector(self.vtx, CTransaction.serialize)

    @classmethod
    def deserialize(cls, r: ByteReader) -> "CBlock":
        header = CBlockHeader.deserialize(r)
        vtx = deser_vector(r, CTransaction.deserialize)
        return cls(header, tuple(vtx))

    @classmethod
    def from_bytes(cls, b: bytes) -> "CBlock":
        r = ByteReader(b)
        blk = cls.deserialize(r)
        if not r.empty():
            raise DeserializationError("trailing bytes after block")
        return blk

    def get_hash(self) -> bytes:
        return self.header.get_hash()

    @property
    def hash_hex(self) -> str:
        return self.header.hash_hex

    def size(self) -> int:
        return len(self.serialize())
