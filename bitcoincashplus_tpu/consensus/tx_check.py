"""Context-free transaction and block sanity checks.

Reference: src/consensus/tx_verify.cpp (CheckTransaction) — 0.15 lineage
moves these out of validation.cpp; same rules either way. Amount rules
from src/amount.h (MoneyRange).
"""

from __future__ import annotations

from .tx import MAX_MONEY, CTransaction, money_range

# Consensus size limits (src/consensus/consensus.h). The BCH-family lineage
# raises the block size cap; we keep it a ChainParams field
# (params.consensus.max_block_size) and use these only as defaults.
MAX_BLOCK_SIZE = 8_000_000  # [fork-delta, hedged] 8MB Bitcoin-Cash-family cap
LEGACY_MAX_BLOCK_SIZE = 1_000_000
MAX_BLOCK_SIGOPS_PER_MB = 20_000
COINBASE_MATURITY = 100  # src/consensus/consensus.h (COINBASE_MATURITY)


class TxValidationError(ValueError):
    """Carries the reference's reject reason string (e.g. 'bad-txns-vin-empty')
    so functional tests can assert on exact reasons like the reference's."""

    def __init__(self, reason: str, debug: str = ""):
        super().__init__(reason + (f" ({debug})" if debug else ""))
        self.reason = reason
        self.debug = debug


LOCKTIME_THRESHOLD = 500_000_000  # script.h: below = height, above = unix time


def is_final_tx(tx: CTransaction, block_height: int, block_time: int) -> bool:
    """IsFinalTx (src/consensus/tx_verify.cpp:~17). ``block_time`` is the
    median-time-past under BIP113 semantics (callers pass MTP)."""
    if tx.locktime == 0:
        return True
    cutoff = block_height if tx.locktime < LOCKTIME_THRESHOLD else block_time
    if tx.locktime < cutoff:
        return True
    return all(txin.sequence == 0xFFFFFFFF for txin in tx.vin)


def check_transaction(tx: CTransaction) -> None:
    """CheckTransaction (src/consensus/tx_verify.cpp:~160): context-free
    sanity. Raises TxValidationError with the reference's reject reason."""
    if not tx.vin:
        raise TxValidationError("bad-txns-vin-empty")
    if not tx.vout:
        raise TxValidationError("bad-txns-vout-empty")
    # Size bound is checked against the serialized size at block level; the
    # per-tx bound mirrors the reference's ::GetSerializeSize check.
    if tx.size() > MAX_BLOCK_SIZE:
        raise TxValidationError("bad-txns-oversize")

    total = 0
    for out in tx.vout:
        if out.value < 0:
            raise TxValidationError("bad-txns-vout-negative")
        if out.value > MAX_MONEY:
            raise TxValidationError("bad-txns-vout-toolarge")
        total += out.value
        if not money_range(total):
            raise TxValidationError("bad-txns-txouttotal-toolarge")

    seen = set()
    for txin in tx.vin:
        if txin.prevout in seen:
            raise TxValidationError("bad-txns-inputs-duplicate")
        seen.add(txin.prevout)

    if tx.is_coinbase():
        if not (2 <= len(tx.vin[0].script_sig) <= 100):
            raise TxValidationError("bad-cb-length")
    else:
        for txin in tx.vin:
            if txin.prevout.is_null():
                raise TxValidationError("bad-txns-prevout-null")
