"""Bitcoin wire serialization primitives.

Re-designs the reference's template-based stream serialization
(src/serialize.h READWRITE macros, src/streams.h CDataStream) as explicit
little-endian codec functions over ``bytes`` / ``memoryview``. The wire format
is consensus-critical and byte-identical to the reference; only the idiom
changes (no C++ template metaprogramming — plain functions + a cursor).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

MAX_SIZE = 0x02000000  # src/serialize.h:~26 (MAX_SIZE) — sanity bound for sizes


class DeserializationError(ValueError):
    """Raised on malformed wire bytes (reference: std::ios_base::failure)."""


@dataclass
class ByteReader:
    """Cursor over immutable bytes — replaces CDataStream's read side."""

    data: memoryview
    pos: int = 0

    def __init__(self, data: bytes | bytearray | memoryview, pos: int = 0):
        self.data = memoryview(data)
        self.pos = pos

    def read(self, n: int) -> memoryview:
        if n < 0 or self.pos + n > len(self.data):
            raise DeserializationError(
                f"read past end: want {n} at {self.pos}, have {len(self.data)}"
            )
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def read_bytes(self, n: int) -> bytes:
        return bytes(self.read(n))

    @property
    def remaining(self) -> int:
        return len(self.data) - self.pos

    def empty(self) -> bool:
        return self.pos >= len(self.data)


# ---- fixed-width little-endian integers ----

def ser_u8(v: int) -> bytes:
    return struct.pack("<B", v)


def ser_u16(v: int) -> bytes:
    return struct.pack("<H", v)


def ser_u32(v: int) -> bytes:
    return struct.pack("<I", v)


def ser_i32(v: int) -> bytes:
    return struct.pack("<i", v)


def ser_u64(v: int) -> bytes:
    return struct.pack("<Q", v)


def ser_i64(v: int) -> bytes:
    return struct.pack("<q", v)


def deser_u8(r: ByteReader) -> int:
    return r.read(1)[0]


def deser_u16(r: ByteReader) -> int:
    return struct.unpack("<H", r.read(2))[0]


def deser_u32(r: ByteReader) -> int:
    return struct.unpack("<I", r.read(4))[0]


def deser_i32(r: ByteReader) -> int:
    return struct.unpack("<i", r.read(4))[0]


def deser_u64(r: ByteReader) -> int:
    return struct.unpack("<Q", r.read(8))[0]


def deser_i64(r: ByteReader) -> int:
    return struct.unpack("<q", r.read(8))[0]


# ---- CompactSize varint (src/serialize.h:~200 WriteCompactSize/ReadCompactSize) ----

def ser_compact_size(n: int) -> bytes:
    if n < 0:
        raise ValueError("negative compact size")
    if n < 253:
        return struct.pack("<B", n)
    if n <= 0xFFFF:
        return b"\xfd" + struct.pack("<H", n)
    if n <= 0xFFFFFFFF:
        return b"\xfe" + struct.pack("<I", n)
    return b"\xff" + struct.pack("<Q", n)


def deser_compact_size(r: ByteReader, range_check: bool = True) -> int:
    tag = r.read(1)[0]
    if tag < 253:
        n = tag
    elif tag == 253:
        n = deser_u16(r)
        if n < 253:
            raise DeserializationError("non-canonical CompactSize")
    elif tag == 254:
        n = deser_u32(r)
        if n < 0x10000:
            raise DeserializationError("non-canonical CompactSize")
    else:
        n = deser_u64(r)
        if n < 0x100000000:
            raise DeserializationError("non-canonical CompactSize")
    if range_check and n > MAX_SIZE:
        raise DeserializationError("CompactSize exceeds MAX_SIZE")
    return n


# ---- variable-length byte strings / vectors ----

def ser_var_bytes(b: bytes) -> bytes:
    return ser_compact_size(len(b)) + b


def deser_var_bytes(r: ByteReader) -> bytes:
    n = deser_compact_size(r)
    return r.read_bytes(n)


def ser_vector(items, ser_item) -> bytes:
    out = [ser_compact_size(len(items))]
    for it in items:
        out.append(ser_item(it))
    return b"".join(out)


def deser_vector(r: ByteReader, deser_item) -> list:
    n = deser_compact_size(r)
    # Do not pre-allocate by claimed n (DoS); items bound the loop naturally.
    return [deser_item(r) for _ in range(n)]


# ---- uint256 <-> bytes helpers (src/uint256.h) ----
# Internal convention: a hash is 32 raw bytes in *wire order* (little-endian of
# the number). Hex display is byte-reversed, matching uint256::GetHex.

def uint256_from_bytes(b: bytes) -> int:
    if len(b) != 32:
        raise ValueError("uint256 needs 32 bytes")
    return int.from_bytes(b, "little")


def uint256_to_bytes(v: int) -> bytes:
    return v.to_bytes(32, "little")


def hash_to_hex(b: bytes) -> str:
    """32 wire bytes -> display hex (reversed), e.g. block hashes in RPC."""
    return bytes(reversed(b)).hex()


def hex_to_hash(s: str) -> bytes:
    """Display hex -> 32 wire bytes."""
    b = bytes.fromhex(s)
    if len(b) != 32:
        raise ValueError("hash hex must be 64 chars")
    return bytes(reversed(b))
