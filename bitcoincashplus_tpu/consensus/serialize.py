"""Bitcoin wire serialization primitives.

Re-designs the reference's template-based stream serialization
(src/serialize.h READWRITE macros, src/streams.h CDataStream) as explicit
little-endian codec functions over ``bytes`` / ``memoryview``. The wire format
is consensus-critical and byte-identical to the reference; only the idiom
changes (no C++ template metaprogramming — plain functions + a cursor).
"""

from __future__ import annotations

import struct

MAX_SIZE = 0x02000000  # src/serialize.h:~26 (MAX_SIZE) — sanity bound for sizes


class DeserializationError(ValueError):
    """Raised on malformed wire bytes (reference: std::ios_base::failure)."""


class ByteReader:
    """Cursor over immutable bytes — replaces CDataStream's read side.
    __slots__ + a cached length: this type's read methods are the hottest
    Python frames in a -reindex (hundreds of calls per transaction), so
    every attribute lookup and len() matters."""

    __slots__ = ("data", "pos", "_len")

    def __init__(self, data: bytes | bytearray | memoryview, pos: int = 0):
        self.data = memoryview(data)
        self.pos = pos
        self._len = len(self.data)

    def read(self, n: int) -> memoryview:
        pos = self.pos
        if n < 0 or pos + n > self._len:
            raise DeserializationError(
                f"read past end: want {n} at {pos}, have {self._len}"
            )
        self.pos = pos + n
        return self.data[pos:pos + n]

    def read_bytes(self, n: int) -> bytes:
        pos = self.pos
        if n < 0 or pos + n > self._len:
            raise DeserializationError(
                f"read past end: want {n} at {pos}, have {self._len}"
            )
        self.pos = pos + n
        return bytes(self.data[pos:pos + n])

    @property
    def remaining(self) -> int:
        return self._len - self.pos

    def empty(self) -> bool:
        return self.pos >= self._len


# ---- fixed-width little-endian integers ----

def ser_u8(v: int) -> bytes:
    return struct.pack("<B", v)


def ser_u16(v: int) -> bytes:
    return struct.pack("<H", v)


def ser_u32(v: int) -> bytes:
    return struct.pack("<I", v)


def ser_i32(v: int) -> bytes:
    return struct.pack("<i", v)


def ser_u64(v: int) -> bytes:
    return struct.pack("<Q", v)


def ser_i64(v: int) -> bytes:
    return struct.pack("<q", v)


# precompiled Structs + unpack_from straight off the memoryview: no slice
# objects, no per-call format parse (reindex-hot)
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_I32 = struct.Struct("<i")
_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")


def deser_u8(r: ByteReader) -> int:
    return r.read(1)[0]


def _deser_fixed(r: ByteReader, st, n: int) -> int:
    pos = r.pos
    if pos + n > r._len:
        raise DeserializationError(
            f"read past end: want {n} at {pos}, have {r._len}"
        )
    r.pos = pos + n
    return st.unpack_from(r.data, pos)[0]


def deser_u16(r: ByteReader) -> int:
    return _deser_fixed(r, _U16, 2)


def deser_u32(r: ByteReader) -> int:
    return _deser_fixed(r, _U32, 4)


def deser_i32(r: ByteReader) -> int:
    return _deser_fixed(r, _I32, 4)


def deser_u64(r: ByteReader) -> int:
    return _deser_fixed(r, _U64, 8)


def deser_i64(r: ByteReader) -> int:
    return _deser_fixed(r, _I64, 8)


# ---- CompactSize varint (src/serialize.h:~200 WriteCompactSize/ReadCompactSize) ----

def ser_compact_size(n: int) -> bytes:
    if n < 0:
        raise ValueError("negative compact size")
    if n < 253:
        return struct.pack("<B", n)
    if n <= 0xFFFF:
        return b"\xfd" + struct.pack("<H", n)
    if n <= 0xFFFFFFFF:
        return b"\xfe" + struct.pack("<I", n)
    return b"\xff" + struct.pack("<Q", n)


def deser_compact_size(r: ByteReader, range_check: bool = True) -> int:
    tag = r.read(1)[0]
    if tag < 253:
        n = tag
    elif tag == 253:
        n = deser_u16(r)
        if n < 253:
            raise DeserializationError("non-canonical CompactSize")
    elif tag == 254:
        n = deser_u32(r)
        if n < 0x10000:
            raise DeserializationError("non-canonical CompactSize")
    else:
        n = deser_u64(r)
        if n < 0x100000000:
            raise DeserializationError("non-canonical CompactSize")
    if range_check and n > MAX_SIZE:
        raise DeserializationError("CompactSize exceeds MAX_SIZE")
    return n


# ---- variable-length byte strings / vectors ----

def ser_var_bytes(b: bytes) -> bytes:
    return ser_compact_size(len(b)) + b


def deser_var_bytes(r: ByteReader) -> bytes:
    n = deser_compact_size(r)
    return r.read_bytes(n)


def ser_vector(items, ser_item) -> bytes:
    out = [ser_compact_size(len(items))]
    for it in items:
        out.append(ser_item(it))
    return b"".join(out)


def deser_vector(r: ByteReader, deser_item) -> list:
    n = deser_compact_size(r)
    # Do not pre-allocate by claimed n (DoS); items bound the loop naturally.
    return [deser_item(r) for _ in range(n)]


# ---- uint256 <-> bytes helpers (src/uint256.h) ----
# Internal convention: a hash is 32 raw bytes in *wire order* (little-endian of
# the number). Hex display is byte-reversed, matching uint256::GetHex.

def uint256_from_bytes(b: bytes) -> int:
    if len(b) != 32:
        raise ValueError("uint256 needs 32 bytes")
    return int.from_bytes(b, "little")


def uint256_to_bytes(v: int) -> bytes:
    return v.to_bytes(32, "little")


def hash_to_hex(b: bytes) -> str:
    """32 wire bytes -> display hex (reversed), e.g. block hashes in RPC."""
    return bytes(reversed(b)).hex()


def hex_to_hash(s: str) -> bytes:
    """Display hex -> 32 wire bytes."""
    b = bytes.fromhex(s)
    if len(b) != 32:
        raise ValueError("hash hex must be 64 chars")
    return bytes(reversed(b))
