"""Consensus layer: serialization, primitives, Merkle, PoW, chain parameters."""
