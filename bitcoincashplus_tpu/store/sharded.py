"""Sharded chainstate store: N hash-partitioned coins backends behind
one CoinsView facade.

The single-writer ``CoinsDB`` commit (store/chainstatedb.py) funnels every
settled batch into one journaled sqlite transaction — the remaining wall
for a production-sized chainstate (ROADMAP "Net effect" after PR 11).
``ShardedCoinsDB`` splits the coin keyspace across N ``KVStore`` backends
(outpoint-keyed, crc32(key) & (N-1), power-of-two N) so one settle's
batch partitions per shard and the sqlite applies + fsyncs run on a
parallel executor. ``CoinsDB`` stays the 1-shard degenerate case;
``ChainstateManager``/``CoinsCache.flush`` route through this facade
untouched above the store seam.

Crash-safety contract (the PR 1 journal, per shard, plus one cross-shard
epoch): every commit carries an epoch stamp E (monotonic, per-shard meta
row ``b"E"`` + the manifest). Step order IS the contract:

  1. per-shard journals made durable, sequentially (fsync-before-rename;
     the ``store_shard`` fault site fires at the head of each leg — a
     failing shard aborts the WHOLE commit and unlinks the journals
     already written, so no shard is ever ahead of the manifest epoch);
  2. per-shard sqlite applies + fsyncs on the executor;
  3. the manifest (``chainstate.manifest.json``) is atomically rewritten
     at epoch E — LAST, so its epoch never names a partially-durable
     commit;
  4. journals cleared.

Recovery (``recover_journal``, duck-typed by ChainstateManager exactly
like the single-shard store): journals all valid at epoch E -> replay
every shard (idempotent) and rewrite the manifest at E; journals partial/
torn -> the crash hit inside step 1, no shard applied anything -> discard
the fragments (rollback; the manifest still names the previous epoch).
Either way every shard lands on ONE consistent epoch — verified by the
sharded hard-kill drill in tests/unit/test_crashsafe_store.py.

Each shard also maintains a MuHash accumulator over its coin rows
(meta row ``b"M"``; store/muhash.py) updated with the commit's batch
delta — the global UTXO-set digest is the product of the shard
accumulators, independent of the shard count, and is what snapshots
stamp and ``gettxoutsetinfo`` surfaces.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np

from ..consensus.tx import COutPoint
from ..util import telemetry as tm
from ..util.faults import INJECTOR, maybe_crash
from ..util.log import log_printf
from ..validation.coins import Coin, CoinsView
from . import muhash
from .chainstatedb import (
    _BEST,
    _COIN,
    _NULL_HASH,
    _coin_key,
    _decode_journal,
    _encode_journal,
    CoinsDB,
)
from .kvstore import KVStore, atomic_write_bytes, atomic_write_json, read_json

# The parallel-flush fault site (util/faults.py STORE_SHARD_SITE):
# explicit-only, fires at the head of every shard's journal leg.
STORE_SHARD_SITE = "store_shard"

_EPOCH = b"E"          # per-shard meta: LE64 commit epoch
_ACC = b"M"            # per-shard meta: 384-byte BE MuHash accumulator
MANIFEST_NAME = "chainstate.manifest.json"

_FLUSH_HIST = tm.histogram(
    "bcp_store_flush_seconds",
    "per-shard chainstate apply+fsync latency inside one parallel flush",
    labels=("shard",),
)
_SHARD_BYTES = tm.gauge(
    "bcp_store_shard_bytes",
    "on-disk bytes per chainstate shard (sqlite main + WAL)",
    labels=("shard",),
)


def shard_of(key36: bytes, n_shards: int) -> int:
    """Hash partition of a 36-byte outpoint key (power-of-two n_shards)."""
    return zlib.crc32(key36) & (n_shards - 1)


class _KeyBloom:
    """Write-side membership filter over a shard's coin keys (ISSUE 20
    satellite, BENCH_r12 follow-up).

    The accumulator delta must divide out every changed row's PERSISTED
    old value — which costs a point lookup per changed key even when the
    key was never persisted (the common case under flood: fresh coin
    creates). The bloom answers "definitely absent" for those keys so
    they skip ``get_serialized_many`` entirely; a maybe-present answer
    falls through to the lookup, so a false positive costs only the old
    price and a false negative is impossible (every persisted key was
    ``add``-ed at its own commit, or at the lazy build scan).

    No hash functions: outpoint keys are txid (32 uniformly random
    bytes) + LE32 vout, so the probes are four 8-byte windows of the key
    itself, each XOR-mixed with an odd-constant multiple of the vout
    word (outputs of one tx share all 32 txid bytes — without the mix
    they would share all four probes). Deterministic across processes
    (no PYTHONHASHSEED), vectorized across the whole batch.
    """

    __slots__ = ("m_bits", "mask", "bits", "added")

    _MIX = (0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F,
            0x165667B19E3779F9, 0x27D4EB2F165667C5)

    def __init__(self, m_bits: int):
        # power-of-two bit count; ~1 MiB per 2^23 bits
        self.m_bits = m_bits
        self.mask = np.uint64(m_bits - 1)
        self.bits = np.zeros(m_bits // 8, dtype=np.uint8)
        self.added = 0

    @classmethod
    def sized(cls, n_keys: int) -> "_KeyBloom":
        """~16 bits/key (4 probes -> ~0.2% FP), 1 Mi-bit floor."""
        m = 1 << 20
        while m < 16 * max(n_keys, 1):
            m *= 2
        return cls(m)

    def _probes(self, keys: list[bytes]) -> list[np.ndarray]:
        flat = np.frombuffer(b"".join(keys), dtype=np.uint8)
        k = flat.reshape(-1, 36)
        vout = k[:, 32:36].copy().view(np.uint32).ravel().astype(np.uint64)
        out = []
        with np.errstate(over="ignore"):
            for j, mix in enumerate(self._MIX):
                w = k[:, 8 * j:8 * j + 8].copy().view(np.uint64).ravel()
                out.append((w ^ (vout * np.uint64(mix))) & self.mask)
        return out

    def add_many(self, keys: list[bytes]) -> None:
        if not keys:
            return
        for probe in self._probes(keys):
            np.bitwise_or.at(
                self.bits, probe >> np.uint64(3),
                np.left_shift(np.uint8(1),
                              (probe & np.uint64(7)).astype(np.uint8)))
        self.added += len(keys)

    def filter(self, keys: list[bytes]) -> list[bytes]:
        """The maybe-present subset of ``keys`` (order preserved)."""
        if not keys:
            return keys
        hit = np.ones(len(keys), dtype=bool)
        for probe in self._probes(keys):
            hit &= (self.bits[probe >> np.uint64(3)]
                    >> (probe & np.uint64(7)).astype(np.uint8)) & 1 > 0
        if bool(hit.all()):
            return keys
        return [k for k, h in zip(keys, hit) if h]

    def saturated(self) -> bool:
        """Adds can only set bits; past ~m/8 keys the FP rate climbs
        toward useless (~2%) — the owner rebuilds bigger from the
        persisted rows."""
        return self.added > self.m_bits // 8


def _shard_paths(datadir: str, i: int) -> tuple[str, str]:
    return (os.path.join(datadir, f"chainstate.shard{i}.sqlite"),
            os.path.join(datadir, f"chainstate.shard{i}.journal"))


class ShardedCoinsDB(CoinsView):
    """The facade: CoinsDB-compatible surface over N shard backends."""

    def __init__(self, datadir: str, n_shards: int = 4, wal: bool = False):
        if n_shards < 1 or n_shards > 256 or (n_shards & (n_shards - 1)):
            raise ValueError(
                f"n_shards={n_shards}: must be a power of two in [1, 256]")
        self.datadir = datadir
        os.makedirs(datadir, exist_ok=True)
        self.manifest_path = os.path.join(datadir, MANIFEST_NAME)
        manifest = read_json(self.manifest_path)
        # an existing store's shard count is a property of the on-disk
        # layout, not of the flag: the manifest wins on reopen
        self.requested_shards = n_shards
        if manifest and int(manifest.get("shards", n_shards)) != n_shards:
            n_shards = int(manifest["shards"])
        self.n_shards = n_shards
        # -coinswal: per-shard WAL commit discipline (store/kvstore) —
        # sync'd shard batches fsync the WAL at COMMIT instead of running
        # a full checkpoint each flush. Operational knob, not layout: the
        # manifest does not pin it, so it can be toggled per restart.
        self.wal = wal
        self.shards: list[CoinsDB] = []
        for i in range(n_shards):
            db_path, journal_path = _shard_paths(datadir, i)
            self.shards.append(
                CoinsDB(KVStore(db_path, wal=wal),
                        journal_path=journal_path))
        self._pool = (ThreadPoolExecutor(
            max_workers=n_shards, thread_name_prefix="coins-shard")
            if n_shards > 1 else None)
        self._accs = [muhash.MuHash.from_bytes(s.kv.get(_ACC))
                      for s in self.shards]
        self._epoch = int(manifest["epoch"]) if manifest else \
            self._max_shard_epoch()
        self._snapshot_state = (manifest or {}).get("snapshot")
        # write-side blooms (ISSUE 20 satellite): per-shard, in-memory
        # only, built lazily at each shard's first commit from the
        # persisted keys; BCP_STORE_BLOOM=0 disables (the A/B knob the
        # utxo_store bench sweeps)
        self.bloom_enabled = os.environ.get("BCP_STORE_BLOOM", "1") != "0"
        self._blooms: list[Optional[_KeyBloom]] = [None] * n_shards
        self.bloom_stats = {"checked": 0, "skipped": 0, "builds": 0,
                            "rebuilds": 0}
        self.last_flush = {"fanout": 0, "seconds": 0.0, "coins": 0,
                           "per_shard_s": []}

    # -- meta helpers ----------------------------------------------------

    def _shard_epoch(self, i: int) -> int:
        raw = self.shards[i].kv.get(_EPOCH)
        return struct.unpack("<Q", raw)[0] if raw else 0

    def _max_shard_epoch(self) -> int:
        return max(self._shard_epoch(i) for i in range(self.n_shards))

    @property
    def epoch(self) -> int:
        return self._epoch

    def muhash_state(self) -> int:
        return muhash.combine(a.state for a in self._accs)

    def muhash_digest(self) -> bytes:
        return muhash.digest_of(self.muhash_state())

    def _write_manifest(self) -> None:
        doc = {
            "version": 1,
            "shards": self.n_shards,
            "epoch": self._epoch,
            "best_block": self.best_block()[::-1].hex(),
            "muhash": self.muhash_digest().hex(),
        }
        if self._snapshot_state is not None:
            doc["snapshot"] = self._snapshot_state
        atomic_write_json(self.manifest_path, doc)

    @property
    def snapshot_state(self) -> Optional[dict]:
        """The assumeutxo onboarding record stamped into the manifest by
        loadtxoutset ({height, hash, digest, validated}); None when this
        chainstate was built by normal IBD."""
        return self._snapshot_state

    def set_snapshot_state(self, state: Optional[dict]) -> None:
        self._snapshot_state = state
        self._write_manifest()

    # -- the commit protocol ---------------------------------------------

    def _commit_sharded(self, entries, best_block: bytes) -> None:
        """entries: iterable of (key36, coin_ser | None-for-delete)."""
        per_puts: list[dict] = [{} for _ in range(self.n_shards)]
        per_dels: list[list] = [[] for _ in range(self.n_shards)]
        n_coins = 0
        for k, ser in entries:
            n_coins += 1
            if ser is None:
                per_dels[shard_of(k, self.n_shards)].append(k)
            else:
                per_puts[shard_of(k, self.n_shards)][k] = ser
        epoch = self._epoch + 1

        # accumulator batch delta, per shard: divide out every changed
        # row's PERSISTED old value (overwrites and spends alike; a
        # tombstone for a never-persisted coin has no old row and costs
        # nothing), multiply in the new values. One modular inverse per
        # shard per commit (muhash.MuHash.apply).
        new_accs = []
        flush_bloom = {"checked": 0, "skipped": 0}
        for i in range(self.n_shards):
            changed = list(per_puts[i]) + per_dels[i]
            # bloom pre-pass: keys the filter proves absent (fresh coin
            # creates, the flood-common case) skip the old-value lookup;
            # false positives just pay the lookup, false negatives are
            # impossible (every persisted key passed through add_many)
            if changed and self.bloom_enabled:
                maybe = self._bloom_for(i).filter(changed)
                flush_bloom["checked"] += len(changed)
                flush_bloom["skipped"] += len(changed) - len(maybe)
            else:
                maybe = changed
            old = self.shards[i].get_serialized_many(maybe) if maybe \
                else {}
            removed = [muhash.coin_element(k, old[k])
                       for k in changed if k in old]
            added = [muhash.coin_element(k, ser)
                     for k, ser in per_puts[i].items()]
            acc = muhash.MuHash(self._accs[i].state)
            acc.apply(added, removed)
            new_accs.append(acc)
            if self.bloom_enabled and per_puts[i]:
                # the new puts become persisted rows below — future
                # commits must see them as maybe-present
                self._bloom_for(i).add_many(list(per_puts[i]))
        self.bloom_stats["checked"] += flush_bloom["checked"]
        self.bloom_stats["skipped"] += flush_bloom["skipped"]

        meta_epoch = struct.pack("<Q", epoch)
        kv_puts = []
        kv_dels = []
        for i in range(self.n_shards):
            puts = {_COIN + k: v for k, v in per_puts[i].items()}
            puts[_BEST] = best_block
            puts[_EPOCH] = meta_epoch
            puts[_ACC] = new_accs[i].to_bytes()
            kv_puts.append(puts)
            kv_dels.append([_COIN + k for k in per_dels[i]])

        # step 1: journals durable, sequentially. A failure here (the
        # store_shard fault site included) aborts the whole commit and
        # unlinks every journal already written this epoch — no shard is
        # ever ahead of the manifest.
        written = []
        try:
            for i, shard in enumerate(self.shards):
                INJECTOR.on_call(STORE_SHARD_SITE)
                atomic_write_bytes(shard.journal_path,
                                   _encode_journal(kv_puts[i], kv_dels[i]))
                maybe_crash("journal:durable")
                written.append(shard.journal_path)
        except BaseException:
            for p in written:
                if os.path.exists(p):
                    os.unlink(p)
            raise
        maybe_crash("shard:journals-durable")

        # step 2: parallel applies. From here the commit only rolls
        # FORWARD — an error leaves the journals in place for replay.
        t0 = time.perf_counter()
        per_shard_s = [0.0] * self.n_shards

        def _apply(i: int) -> None:
            ta = time.perf_counter()
            self.shards[i].kv.write_batch(kv_puts[i], kv_dels[i], sync=True)
            dt = time.perf_counter() - ta
            per_shard_s[i] = dt
            _FLUSH_HIST.labels(shard=str(i)).observe(dt)

        if self._pool is not None:
            futures = [self._pool.submit(_apply, i)
                       for i in range(self.n_shards)]
            for f in futures:
                f.result()
        else:
            _apply(0)
        maybe_crash("shard:applied")

        # step 3: the cross-shard epoch marker, written last
        self._accs = new_accs
        self._epoch = epoch
        self._write_manifest()
        maybe_crash("manifest:written")

        # step 4: clear
        for shard in self.shards:
            maybe_crash("journal:pre-clear")
            if os.path.exists(shard.journal_path):
                os.unlink(shard.journal_path)

        self.last_flush = {
            "fanout": self.n_shards,
            "seconds": time.perf_counter() - t0,
            "coins": n_coins,
            "per_shard_s": [round(s, 6) for s in per_shard_s],
            "bloom": flush_bloom,
        }
        for i in range(self.n_shards):
            _SHARD_BYTES.labels(shard=str(i)).set(self.shard_bytes(i))

    def recover_journal(self) -> bool:
        """Startup replay/rollback across every shard, landing all of
        them on one epoch. Called by ChainstateManager.__init__ via the
        same duck-typed hook as the single-shard store."""
        for p in (self.manifest_path + ".tmp",):
            if os.path.exists(p):
                os.unlink(p)
        decoded: list[Optional[tuple]] = []
        for shard in self.shards:
            tmp = shard.journal_path + ".tmp"
            if os.path.exists(tmp):
                os.unlink(tmp)  # pre-durability fragment
            if not os.path.exists(shard.journal_path):
                decoded.append(None)
                continue
            with open(shard.journal_path, "rb") as f:
                data = f.read()
            d = _decode_journal(data)
            if d is None:
                log_printf("shard journal torn (%s) — rolling back",
                           os.path.basename(shard.journal_path))
                os.unlink(shard.journal_path)
            decoded.append(d)
        if not any(d is not None for d in decoded):
            return False

        valid = [d for d in decoded if d is not None]
        epoch = struct.unpack("<Q", valid[0][0][_EPOCH])[0]
        if len(valid) < self.n_shards:
            # partial journal set: the crash hit while step 1 was still
            # writing journals — unless a journal-less shard already
            # carries epoch E, in which case the journals vanished in
            # step 4 and the valid remainder just replays.
            applied_without_journal = any(
                decoded[i] is None and self._shard_epoch(i) >= epoch
                for i in range(self.n_shards))
            if not applied_without_journal:
                if any(self._shard_epoch(i) >= epoch
                       for i in range(self.n_shards)):
                    # a shard reached epoch E while a journal-less peer is
                    # still behind it: impossible under the step order
                    # (applies only start once EVERY journal is durable)
                    raise RuntimeError(
                        "sharded chainstate inconsistent: shard ahead of "
                        "a journal-less peer")
                for i, d in enumerate(decoded):
                    if d is not None and \
                            os.path.exists(self.shards[i].journal_path):
                        os.unlink(self.shards[i].journal_path)
                log_printf("sharded commit rolled back: %d/%d journals "
                           "durable at epoch %d", len(valid), self.n_shards,
                           epoch)
                return False
        # replay: every journal present (or the absent ones already
        # applied + cleared). Idempotent per shard.
        n_puts = n_dels = 0
        for i, d in enumerate(decoded):
            if d is None:
                continue
            puts, dels = d
            self.shards[i].kv.write_batch(puts, dels, sync=True)
            n_puts += len(puts)
            n_dels += len(dels)
        self._accs = [muhash.MuHash.from_bytes(s.kv.get(_ACC))
                      for s in self.shards]
        self._epoch = epoch
        self._write_manifest()
        for i, d in enumerate(decoded):
            if d is not None and \
                    os.path.exists(self.shards[i].journal_path):
                os.unlink(self.shards[i].journal_path)
        log_printf("sharded journal replayed at epoch %d: %d put(s), "
                   "%d delete(s) across %d shard(s)",
                   epoch, n_puts, n_dels, self.n_shards)
        return True

    # -- CoinsDB-compatible surface --------------------------------------

    def _bloom_for(self, i: int) -> _KeyBloom:
        """The shard's bloom, built at first use from the persisted keys
        (one full key scan per shard per process) and rebuilt bigger
        when adds saturate it."""
        b = self._blooms[i]
        if b is not None and b.saturated():
            self.bloom_stats["rebuilds"] += 1
            b = None
        if b is None:
            keys = [k for k, _ in self.iterate_shard_coins(i)]
            b = _KeyBloom.sized(max(len(keys) * 2, 1))
            b.add_many(keys)
            self._blooms[i] = b
            self.bloom_stats["builds"] += 1
        return b

    def _shard_for(self, key36: bytes) -> CoinsDB:
        return self.shards[shard_of(key36, self.n_shards)]

    def get_coin(self, outpoint: COutPoint) -> Optional[Coin]:
        return self._shard_for(_coin_key(outpoint)[1:]).get_coin(outpoint)

    def have_coin(self, outpoint: COutPoint) -> bool:
        return self._shard_for(_coin_key(outpoint)[1:]).have_coin(outpoint)

    def best_block(self) -> bytes:
        return self.shards[0].kv.get(_BEST) or _NULL_HASH

    def batch_write(self, coins: dict, best_block: bytes) -> None:
        self._commit_sharded(
            ((op.hash + struct.pack("<I", op.n),
              None if coin is None else coin.serialize())
             for op, coin in coins.items()),
            best_block)

    def batch_write_serialized(self, entries, best_block: bytes) -> None:
        self._commit_sharded(entries, best_block)

    def get_serialized_many(self, keys36: list[bytes]) -> dict[bytes, bytes]:
        per: list[list[bytes]] = [[] for _ in range(self.n_shards)]
        for k in keys36:
            per[shard_of(k, self.n_shards)].append(k)
        out: dict[bytes, bytes] = {}
        for i, keys in enumerate(per):
            if keys:
                out.update(self.shards[i].get_serialized_many(keys))
        return out

    def count_coins(self) -> int:
        return sum(s.count_coins() for s in self.shards)

    def iterate_coins(self) -> Iterator[tuple[bytes, bytes]]:
        """(key36, coin_ser) over every shard — shard-major order; the
        consumers (gettxoutsetinfo, snapshot dump, digest recompute) are
        order-independent."""
        for shard in self.shards:
            for k, v in shard.kv.iterate(_COIN):
                yield k[1:], v

    def iterate_shard_coins(self, i: int) -> Iterator[tuple[bytes, bytes]]:
        for k, v in self.shards[i].kv.iterate(_COIN):
            yield k[1:], v

    # -- snapshot bulk load ----------------------------------------------

    def ingest_rows(self, rows: list[tuple[bytes, bytes]]) -> None:
        """Journal-less bulk insert for snapshot onboarding (the caller
        finalizes with meta + manifest once the digest verifies)."""
        per: list[dict] = [{} for _ in range(self.n_shards)]
        for k, ser in rows:
            per[shard_of(k, self.n_shards)][_COIN + k] = ser

        def _load(i: int) -> None:
            if per[i]:
                self.shards[i].kv.write_batch(per[i])

        if self._pool is not None:
            for f in [self._pool.submit(_load, i)
                      for i in range(self.n_shards)]:
                f.result()
        else:
            _load(0)
        # bulk rows bypassed the commit path: rebuild lazily on next use
        self._blooms = [None] * self.n_shards

    def clear_coins(self) -> None:
        """Drop every coin row (failed snapshot load cleanup)."""
        for shard in self.shards:
            dels = [k for k, _ in shard.kv.iterate(_COIN)]
            for i in range(0, len(dels), 10000):
                shard.kv.write_batch({}, dels[i:i + 10000])
        self._blooms = [None] * self.n_shards

    def finalize_bulk_load(self, best_block: bytes,
                           shard_states: list[int],
                           snapshot: Optional[dict] = None) -> None:
        """Stamp meta rows + manifest after a verified bulk load."""
        assert len(shard_states) == self.n_shards
        epoch = self._epoch + 1
        meta_epoch = struct.pack("<Q", epoch)
        for i, shard in enumerate(self.shards):
            shard.kv.write_batch({
                _BEST: best_block,
                _EPOCH: meta_epoch,
                _ACC: muhash.MuHash(shard_states[i]).to_bytes(),
            }, sync=True)
        self._accs = [muhash.MuHash(s) for s in shard_states]
        self._epoch = epoch
        self._snapshot_state = snapshot
        self._write_manifest()

    # -- observability ---------------------------------------------------

    def shard_bytes(self, i: int) -> int:
        db_path, _ = _shard_paths(self.datadir, i)
        total = 0
        for suffix in ("", "-wal"):
            try:
                total += os.path.getsize(db_path + suffix)
            except OSError:
                pass
        return total

    def recompute_digest(self) -> bytes:
        """From-scratch digest over the persisted rows (test oracle for
        the incrementally-maintained accumulator)."""
        elems = [muhash.coin_element(k, v) for k, v in self.iterate_coins()]
        return muhash.digest_of(muhash.batch_product(elems))

    def stats(self) -> dict:
        return {
            "shards": self.n_shards,
            "wal": self.wal,
            "epoch": self._epoch,
            "muhash": self.muhash_digest().hex(),
            "bloom": {"enabled": self.bloom_enabled, **self.bloom_stats},
            "last_flush": dict(self.last_flush),
            "shard_bytes": [self.shard_bytes(i)
                            for i in range(self.n_shards)],
            "snapshot": self._snapshot_state,
        }

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
        for shard in self.shards:
            shard.kv.close()

    @staticmethod
    def wipe(datadir: str) -> None:
        """Remove every shard/manifest artifact (the -reindex wipe)."""
        import glob as _glob

        for p in _glob.glob(os.path.join(datadir, "chainstate.shard*")):
            os.remove(p)
        for p in (os.path.join(datadir, MANIFEST_NAME),
                  os.path.join(datadir, MANIFEST_NAME + ".tmp")):
            if os.path.exists(p):
                os.remove(p)
