"""CDBWrapper-shaped key-value store over sqlite3.

Reference: src/dbwrapper.{h,cpp} (CDBWrapper, CDBBatch, CDBIterator) over
LevelDB. sqlite3 (WAL mode) provides the same contract this framework needs:
ordered byte-key iteration, atomic batch writes, durable sync on request.
The obfuscation-key machinery of the reference (anti-virus false-positive
mitigation) is intentionally dropped — it has no behavioral surface.
"""

from __future__ import annotations

import json
import os
import sqlite3
from typing import Iterator, Optional

from ..util import lockwatch
from ..util.faults import maybe_crash


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Durable file publish with fsync-before-rename semantics: write to a
    sibling .tmp, flush+fsync the data, atomically rename over ``path``,
    then fsync the directory so the rename itself is durable. A crash at
    any point leaves either the old file (or no file) or the complete new
    one — never a torn write. Used by the chainstate commit journal
    (store/chainstatedb.py). Crash points (util/faults.maybe_crash) let
    tests kill the process between each step."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    maybe_crash("journal:tmp-written")
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def atomic_write_json(path: str, obj) -> None:
    """JSON flavor of :func:`atomic_write_bytes` — the durable publish used
    by the small operational sidecar files (banlist.json, like the
    reference's banman.cpp DumpBanlist)."""
    atomic_write_bytes(
        path, json.dumps(obj, sort_keys=True, indent=1).encode()
    )


def read_json(path: str, default=None):
    """Load a JSON sidecar written by :func:`atomic_write_json`; a missing
    or corrupt file yields ``default`` (startup must never die on an
    operational sidecar — the reference logs and recreates banlist.dat)."""
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode())
    except (OSError, ValueError):
        return default


class KVStore:
    def __init__(self, path: str, wal: bool = False):
        # isolation_level=None -> explicit transaction control.
        # check_same_thread=False: RPC worker threads reach the store.
        # Most access serializes under the node's cs_main, but not ALL of
        # it — node INIT keeps working while the background txindex
        # backfill thread writes under cs_main, and two overlapping
        # BEGIN/COMMIT spans on ONE sqlite connection raise ("cannot start
        # a transaction within a transaction"). The store owns its write
        # lock so atomicity never depends on every caller's locking.
        # Named per-file in the lockwatch graph so two stores' locks are
        # never conflated into a false ordering edge.
        self._write_lock = lockwatch.watched_lock(
            "kvstore:%s" % os.path.basename(path))
        self._db = sqlite3.connect(path, isolation_level=None,
                                   check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        # wal=False (default): synchronous=NORMAL + an explicit
        # wal_checkpoint(FULL) on every sync'd batch — the checkpoint IS
        # the durability boundary. wal=True (-coinswal): the WAL itself
        # is the durability boundary — synchronous=FULL makes each COMMIT
        # fsync the WAL, sync'd batches skip the (expensive, serializing)
        # per-commit checkpoint, and sqlite's auto-checkpoint folds the
        # WAL back at its leisure. Committed transactions are equally
        # durable either way; the knob trades checkpoint latency in the
        # parallel per-shard flush for WAL-fsync latency at commit.
        self.wal = wal
        self._db.execute("PRAGMA synchronous=%s"
                         % ("FULL" if wal else "NORMAL"))
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
        )

    def get(self, key: bytes) -> Optional[bytes]:
        row = self._db.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def get_many(self, keys: list[bytes]) -> dict[bytes, bytes]:
        """Present rows for ``keys`` in one query per 500 keys (sqlite's
        bound-parameter limit is 999) — the block-import miss-fetch path."""
        out: dict[bytes, bytes] = {}
        for i in range(0, len(keys), 500):
            chunk = keys[i:i + 500]
            q = ("SELECT k, v FROM kv WHERE k IN (%s)"
                 % ",".join("?" * len(chunk)))
            for k, v in self._db.execute(q, chunk):
                out[k] = v
        return out

    def put(self, key: bytes, value: bytes) -> None:
        # under the write lock: a lone put during another thread's open
        # BEGIN would otherwise join (and possibly roll back with) that
        # transaction on this shared connection — ADVICE r4
        with self._write_lock:
            self._db.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                (key, value),
            )

    def delete(self, key: bytes) -> None:
        with self._write_lock:
            self._db.execute("DELETE FROM kv WHERE k = ?", (key,))

    def exists(self, key: bytes) -> bool:
        return self.get(key) is not None

    def write_batch(self, puts: dict[bytes, bytes], deletes: list[bytes] = (),
                    sync: bool = False) -> None:
        """CDBBatch + WriteBatch: all-or-nothing (one sqlite transaction)."""
        with self._write_lock:
            cur = self._db.cursor()
            cur.execute("BEGIN")
            maybe_crash("kv:begin")
            try:
                if deletes:
                    cur.executemany("DELETE FROM kv WHERE k = ?",
                                    [(k,) for k in deletes])
                if puts:
                    cur.executemany(
                        "INSERT INTO kv (k, v) VALUES (?, ?) "
                        "ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                        list(puts.items()),
                    )
                # a hard kill here leaves an uncommitted WAL transaction
                # that sqlite discards on reopen — the torn-commit case the
                # crash-injection tests cover
                maybe_crash("kv:applied")
                cur.execute("COMMIT")
                maybe_crash("kv:committed")
            except BaseException:
                cur.execute("ROLLBACK")
                raise
            if sync and not self.wal:
                self._db.execute("PRAGMA wal_checkpoint(FULL)")

    def iterate(self, prefix: bytes = b"") -> Iterator[tuple[bytes, bytes]]:
        """Ordered iteration over keys with the given prefix — CDBIterator."""
        hi = _prefix_upper_bound(prefix) if prefix else None
        if prefix and hi is not None:
            cur = self._db.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k", (prefix, hi)
            )
        elif prefix:  # all-0xFF prefix: no finite upper bound
            cur = self._db.execute(
                "SELECT k, v FROM kv WHERE k >= ? ORDER BY k", (prefix,)
            )
        else:
            cur = self._db.execute("SELECT k, v FROM kv ORDER BY k")
        yield from cur

    def close(self) -> None:
        self._db.close()


def _prefix_upper_bound(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every key starting with `prefix`
    (carry-increment, dropping trailing 0xFF bytes); None if prefix is all
    0xFF, which has no finite bound."""
    trimmed = prefix.rstrip(b"\xff")
    if not trimmed:
        return None
    return trimmed[:-1] + bytes([trimmed[-1] + 1])
