"""UTXO snapshot serialization — the assumeutxo onboarding format.

Reference: Bitcoin Core's dumptxoutset/loadtxoutset (node/utxo_snapshot.h)
reshaped for the sharded store: a snapshot is a DIRECTORY holding

  MANIFEST.json   version, network, height, best block hash, coin count,
                  the MuHash set digest, and per-file sha256 checksums
  headers.dat     the 80-byte headers genesis..tip, concatenated — the
                  loading node installs these through the normal
                  accept_block_header PoW checks, no trust needed
  utxo-NN.dat     one stream per source shard: repeated
                  (key36 | LE32 value-length | Coin serialization)

The digest is partition-independent (store/muhash.py), so a snapshot
dumped from an N-shard store loads into an M-shard store: rows are
re-partitioned by the target's shard function while the set digest is
recomputed and must match the manifest AND the operator-supplied
``-assumeutxo=<hash:digest>`` authorization before any of it becomes the
node's chainstate.
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Optional

from ..util.log import log_printf
from . import muhash
from .certificate import CERT_NAME, CertificateError, verify_certificate
from .kvstore import atomic_write_json, read_json
from .sharded import ShardedCoinsDB, shard_of

SNAPSHOT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
HEADERS_NAME = "headers.dat"
_ROW_HDR = struct.Struct("<36sI")
_CHUNK_ROWS = 16384


class SnapshotError(Exception):
    """A snapshot that failed structural or digest verification."""


def _shard_streams(coins_db):
    """[(stream_index, row_iterator)] for any coins backend."""
    if isinstance(coins_db, ShardedCoinsDB):
        return [(i, coins_db.iterate_shard_coins(i))
                for i in range(coins_db.n_shards)]
    return [(0, coins_db.iterate_coins())]


def dump_snapshot(coins_db, path: str, headers: list[bytes],
                  height: int, best_block: bytes, network: str,
                  certificate: Optional[dict] = None) -> dict:
    """Write a snapshot directory at ``path`` from the PERSISTED coin set
    (the caller flushes first). When the dumping node supplies a
    proof-carrying ``certificate`` (store/certificate.py) it is written
    alongside as CERTIFICATE.json — self-authenticating via its own
    commitment chain, so the manifest does not checksum it. Returns the
    manifest dict."""
    os.makedirs(path, exist_ok=True)
    hdr_blob = b"".join(headers)
    with open(os.path.join(path, HEADERS_NAME), "wb") as f:
        f.write(hdr_blob)

    files = []
    total_coins = 0
    acc = 1
    elems: list[int] = []
    for stream_i, rows in _shard_streams(coins_db):
        name = f"utxo-{stream_i:02d}.dat"
        h = hashlib.sha256()
        n = 0
        nbytes = 0
        with open(os.path.join(path, name), "wb") as f:
            for key36, ser in rows:
                rec = _ROW_HDR.pack(key36, len(ser)) + ser
                f.write(rec)
                h.update(rec)
                n += 1
                nbytes += len(rec)
                elems.append(muhash.coin_element(key36, ser))
                if len(elems) >= _CHUNK_ROWS:
                    acc = acc * muhash.batch_product(elems) % muhash.MUHASH_P
                    elems = []
        total_coins += n
        files.append({"name": name, "coins": n, "bytes": nbytes,
                      "sha256": h.hexdigest()})
    if elems:
        acc = acc * muhash.batch_product(elems) % muhash.MUHASH_P

    manifest = {
        "version": SNAPSHOT_VERSION,
        "network": network,
        "height": height,
        "best_block": best_block[::-1].hex(),
        "coins": total_coins,
        "muhash": muhash.digest_of(acc).hex(),
        "files": files,
        "headers": {"file": HEADERS_NAME, "count": len(headers),
                    "sha256": hashlib.sha256(hdr_blob).hexdigest()},
    }
    atomic_write_json(os.path.join(path, MANIFEST_NAME), manifest)
    if certificate is not None:
        atomic_write_json(os.path.join(path, CERT_NAME), certificate)
    log_printf("dumptxoutset: %d coins at height %d -> %s (digest %s%s)",
               total_coins, height, path, manifest["muhash"][:16],
               ", certified" if certificate is not None else "")
    return manifest


def _iter_rows(path: str, expect_sha: str):
    """Yield (key36, ser) records from one utxo stream, verifying the
    file checksum as a side effect (raises SnapshotError at EOF on
    mismatch or on a torn record)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            head = f.read(_ROW_HDR.size)
            if not head:
                break
            if len(head) < _ROW_HDR.size:
                raise SnapshotError(f"torn record header in {path}")
            key36, vlen = _ROW_HDR.unpack(head)
            ser = f.read(vlen)
            if len(ser) < vlen:
                raise SnapshotError(f"torn record value in {path}")
            h.update(head)
            h.update(ser)
            yield key36, ser
    if h.hexdigest() != expect_sha:
        raise SnapshotError(f"checksum mismatch for {path}")


def load_snapshot(path: str, coins_db: ShardedCoinsDB, network: str,
                  expected_hash: Optional[bytes] = None,
                  expected_digest: Optional[bytes] = None,
                  require_certificate: bool = False) -> dict:
    """Stream a snapshot into ``coins_db`` (re-partitioned to its shard
    count), verify the recomputed set digest against the manifest and the
    operator authorization BEFORE stamping any chainstate meta, and
    return {height, best_block, headers(list of 80-byte blobs),
    manifest, certificate, cert_checkpoints}. On any failure the loaded
    rows are wiped.

    If the snapshot carries CERTIFICATE.json it is verified BEFORE a
    single row is streamed: wrong MMR root over the snapshot's own
    headers, truncated epoch trajectory, or a bit-flipped certificate all
    raise SnapshotError and take the same wipe-and-reject path as a wrong
    set digest — the chainstate is never half-loaded. On success
    ``cert_checkpoints`` maps epoch height -> expected MuHash digest hex
    for the background shadow validator to check itself against as it
    replays. ``require_certificate`` (``-snapshotcertrequired``) refuses
    certificate-less snapshots outright; without it they still load but
    the node quarantines them from serving until fully validated."""
    manifest = read_json(os.path.join(path, MANIFEST_NAME))
    if not manifest or manifest.get("version") != SNAPSHOT_VERSION:
        raise SnapshotError(f"missing or unreadable {MANIFEST_NAME}")
    if manifest.get("network") != network:
        raise SnapshotError(
            f"snapshot network {manifest.get('network')!r} != {network!r}")
    best_block = bytes.fromhex(manifest["best_block"])[::-1]
    if expected_hash is not None and best_block != expected_hash:
        raise SnapshotError(
            "snapshot tip %s does not match the -assumeutxo hash" %
            manifest["best_block"][:16])
    if expected_digest is not None and \
            manifest["muhash"] != expected_digest.hex():
        raise SnapshotError(
            "snapshot manifest digest does not match -assumeutxo")

    hdr_path = os.path.join(path, manifest["headers"]["file"])
    with open(hdr_path, "rb") as f:
        hdr_blob = f.read()
    if hashlib.sha256(hdr_blob).hexdigest() != manifest["headers"]["sha256"] \
            or len(hdr_blob) != 80 * manifest["headers"]["count"]:
        raise SnapshotError("headers stream corrupt")
    headers = [hdr_blob[i:i + 80] for i in range(0, len(hdr_blob), 80)]

    certificate = read_json(os.path.join(path, CERT_NAME))
    if require_certificate and not certificate:
        raise SnapshotError(
            "snapshot carries no certificate and -snapshotcertrequired is "
            "set — refusing trust-me onboarding")

    n = coins_db.n_shards
    shard_states = [1] * n
    pending_elems: list[list[int]] = [[] for _ in range(n)]
    rows: list[tuple[bytes, bytes]] = []
    total = 0

    def _flush_rows():
        nonlocal rows
        coins_db.ingest_rows(rows)
        rows = []
        for i in range(n):
            if pending_elems[i]:
                shard_states[i] = (shard_states[i] *
                                   muhash.batch_product(pending_elems[i])
                                   ) % muhash.MUHASH_P
                pending_elems[i] = []

    cert_checkpoints: Optional[dict] = None
    try:
        if certificate:
            # fail-fast leg: a bad certificate costs seconds (batched
            # header-MMR recompute + one hash chain), not a streamed-in
            # chainstate — and any failure still exits through the same
            # clear_coins() wipe as a digest mismatch, so a fault-injected
            # mid-verify crash (snapshot_cert fail-*) provably cannot
            # leave rows behind
            from ..crypto.hashes import sha256d
            try:
                cert_checkpoints = verify_certificate(
                    certificate, [sha256d(h) for h in headers],
                    manifest["height"], manifest["muhash"])
            except CertificateError as e:
                raise SnapshotError(f"snapshot certificate rejected: {e}")
            log_printf("loadtxoutset: certificate verified (%d epoch "
                       "checkpoints, stride %d)", len(cert_checkpoints),
                       certificate["epoch_blocks"])
        for entry in manifest["files"]:
            for key36, ser in _iter_rows(os.path.join(path, entry["name"]),
                                         entry["sha256"]):
                rows.append((key36, ser))
                pending_elems[shard_of(key36, n)].append(
                    muhash.coin_element(key36, ser))
                total += 1
                if len(rows) >= _CHUNK_ROWS:
                    _flush_rows()
        _flush_rows()
        if total != manifest["coins"]:
            raise SnapshotError(
                f"coin count {total} != manifest {manifest['coins']}")
        digest = muhash.digest_of(muhash.combine(shard_states))
        if digest.hex() != manifest["muhash"]:
            raise SnapshotError(
                "recomputed set digest does not match the manifest")
        if expected_digest is not None and digest != expected_digest:
            raise SnapshotError(
                "recomputed set digest does not match -assumeutxo")
    except Exception:
        coins_db.clear_coins()
        raise

    coins_db.finalize_bulk_load(
        best_block, shard_states,
        snapshot={"height": manifest["height"],
                  "hash": manifest["best_block"],
                  "digest": manifest["muhash"],
                  "validated": False,
                  "cert": {"present": bool(certificate),
                           "verified": bool(certificate),
                           "epoch_blocks": (certificate or {}).get(
                               "epoch_blocks", 0),
                           "epochs": len(cert_checkpoints or {})}})
    log_printf("loadtxoutset: %d coins at height %d (digest %s) — "
               "serving at the snapshot tip, history pending%s",
               total, manifest["height"], manifest["muhash"][:16],
               "" if certificate else
               " (UNCERTIFIED: quarantined from fleet serving until "
               "fully validated)")
    return {"height": manifest["height"], "best_block": best_block,
            "headers": headers, "manifest": manifest,
            "certificate": certificate,
            "cert_checkpoints": cert_checkpoints}
