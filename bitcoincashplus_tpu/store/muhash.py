"""MuHash-style multiplicative UTXO-set accumulator.

Reference: src/crypto/muhash.{h,cpp} (MuHash3072, BIP-UTXO set hashing)
and PAPERS.md 2407.03511 — the snapshot commitment is structured as an
incrementally-hashable accumulator so a succinct proof could later attest
the same digest the node maintains live.

The set hash of a multiset S of byte strings is

    H(S) = sha256( BE384( prod_{x in S} elem(x)  mod p ) )

with p = 2^3072 - 1103717 (the MuHash3072 prime) and elem(x) a hash-to-
group map (SHAKE256 expansion of x to 384 bytes, reduced mod p). The
group is (Z/pZ)*, so:

  - insertion multiplies the accumulator by elem(x);
  - removal multiplies by elem(x)^-1 (one modular inverse per batch —
    removed elements are multiplied together first);
  - the hash is order- and partition-independent: a sharded store keeps
    one accumulator per shard and the global digest is the product of the
    shard accumulators, identical for every shard count.

Two batch-product backends, differential-tested against each other:

  - `batch_product_ref`: plain python ints (CPython's native big-int
    multiply);
  - `_batch_product_limbs`: numpy 16-bit-limb rows (192 limbs, pairwise
    tree reduction with a shift-add schoolbook multiply — partial sums
    bounded by 192 * (2^16-1)^2 < 2^40, far under uint64 — a sequential
    carry sweep, and a fold-based reduction using 2^3072 ≡ 1103717
    mod p). The limb layout is the vector-unit-friendly form.

`batch_product` dispatches between them. Measured on the bench host
(single core), CPython's int multiply wins at every batch size — 22 µs
vs ~270 µs per element at 50k elements; the limb path's per-level python
loop over 192 limb positions dominates — so the int path is the default
and BCP_MUHASH_LIMBS=1 opts in to the limb backend. stdlib+numpy only —
importable from the jax-free crash-test workers.
"""

from __future__ import annotations

import hashlib
import os
from typing import Iterable, Optional

try:
    import numpy as _np
except Exception:  # pragma: no cover - numpy is part of the baked image
    _np = None

# The MuHash3072 prime: 2^3072 - 1103717.
MUHASH_C = 1103717
MUHASH_P = (1 << 3072) - MUHASH_C

_ND = 192          # 3072 bits / 16-bit limbs
_LIMB_MASK = 0xFFFF

# p as little-endian 16-bit limbs, for the vectorized compare/subtract.
_P_LIMBS = None
if _np is not None:
    _P_LIMBS = _np.frombuffer(
        MUHASH_P.to_bytes(384, "little"), dtype="<u2"
    ).astype(_np.uint64)


def element(data: bytes) -> int:
    """Hash-to-group: SHAKE256(data) expanded to 384 bytes, reduced mod p.
    Never returns 0 (0 is not in the multiplicative group)."""
    v = int.from_bytes(hashlib.shake_256(data).digest(384), "little")
    v %= MUHASH_P
    return v if v else 1


def coin_element(key36: bytes, coin_ser: bytes) -> int:
    """The accumulator element for one UTXO row: outpoint key (32-byte
    txid + LE32 index) followed by the Coin serialization — exactly the
    bytes the sharded store persists, so a from-scratch recompute over
    `iterate_coins()` reproduces the live digest."""
    return element(key36 + coin_ser)


def digest_of(acc: int) -> bytes:
    """32-byte set digest of an accumulator value (big-endian 384-byte
    serialization, sha256'd)."""
    return hashlib.sha256((acc % MUHASH_P).to_bytes(384, "big")).digest()


# -- python-int reference path ---------------------------------------------

def batch_product_ref(values: Iterable[int]) -> int:
    acc = 1
    for v in values:
        acc = (acc * v) % MUHASH_P
    return acc


# -- numpy limb path -------------------------------------------------------

def _to_limbs(values: list[int]):
    rows = _np.empty((len(values), _ND), dtype=_np.uint64)
    for i, v in enumerate(values):
        rows[i] = _np.frombuffer(v.to_bytes(384, "little"), dtype="<u2")
    return rows


def _from_limbs(row) -> int:
    return int.from_bytes(row.astype("<u2").tobytes(), "little")


def _carry_sweep(acc):
    """Normalize partial sums to 16-bit limbs in place; returns acc."""
    carry = _np.zeros(acc.shape[0], dtype=_np.uint64)
    for j in range(acc.shape[1]):
        t = acc[:, j] + carry
        acc[:, j] = t & _LIMB_MASK
        carry = t >> 16
    assert not carry.any()  # columns sized so the top carry is always 0
    return acc


def _mul_pairs(xs, ys):
    """Schoolbook multiply of paired rows -> (B, 2*_ND + 1) limb rows.
    Each partial sum is <= 192 * (2^16-1)^2 < 2^40: no uint64 overflow."""
    n = xs.shape[0]
    acc = _np.zeros((n, 2 * _ND + 1), dtype=_np.uint64)
    for i in range(_ND):
        acc[:, i:i + _ND] += xs[:, i:i + 1] * ys
    return _carry_sweep(acc)


def _fold(rows):
    """One reduction fold: x = hi * 2^3072 + lo  ->  hi * c + lo  (mod p
    unchanged). Input (B, W) limbs with W > _ND; output (B, W') with
    W' < W. Repeating until W == _ND leaves values < 2^3072 + small."""
    lo = rows[:, :_ND]
    hi = rows[:, _ND:]
    w = hi.shape[1] + 2  # hi*c grows by at most 21 bits (< 2 limbs)
    acc = _np.zeros((rows.shape[0], max(w, _ND + 1)), dtype=_np.uint64)
    acc[:, :hi.shape[1]] = hi * MUHASH_C  # <= (2^16-1)*c < 2^37 per limb
    acc[:, :_ND] += lo
    return _carry_sweep(acc)


def _reduce_mod_p(rows):
    """Full reduction of (B, W) limb rows to canonical residues (B, _ND)."""
    while rows.shape[1] > _ND:
        folded = _fold(rows)
        # strip limbs that went to zero at the top so the loop terminates
        top = folded.shape[1]
        while top > _ND and not folded[:, top - 1].any():
            top -= 1
        rows = folded[:, :top]
    # rows < 2^3072 now; subtract p where rows >= p (at most once, since
    # 2^3072 < 2p). Vectorized big-endian compare, then borrow-subtract.
    gt_mask = _np.zeros(rows.shape[0], dtype=bool)
    lt_mask = _np.zeros(rows.shape[0], dtype=bool)
    for j in range(_ND - 1, -1, -1):
        undecided = ~(gt_mask | lt_mask)
        gt_mask |= undecided & (rows[:, j] > _P_LIMBS[j])
        lt_mask |= undecided & (rows[:, j] < _P_LIMBS[j])
    ge = ~lt_mask  # equal-all-the-way counts as >= p too
    if ge.any():
        sub = rows[ge]
        borrow = _np.zeros(sub.shape[0], dtype=_np.uint64)
        base = _np.uint64(1 << 16)
        for j in range(_ND):
            t = sub[:, j] + base - _P_LIMBS[j] - borrow
            sub[:, j] = t & _LIMB_MASK
            borrow = _np.uint64(1) - (t >> 16)
        rows[ge] = sub
    return rows


def _batch_product_limbs(values: list[int]) -> int:
    """prod(values) mod p via the numpy limb rows (pairwise tree
    reduction). Equal to :func:`batch_product_ref` always — the unit
    suite asserts it on random and near-p inputs."""
    rows = _reduce_mod_p(_to_limbs(values))
    while rows.shape[0] > 1:
        k = rows.shape[0] // 2
        prod = _reduce_mod_p(_mul_pairs(rows[0:2 * k:2], rows[1:2 * k:2]))
        if rows.shape[0] % 2:
            prod = _np.concatenate([prod, rows[-1:]], axis=0)
        rows = prod
    return _from_limbs(rows[0])


# Opt-in to the limb backend for the live accumulator. Default off: the
# int path measured faster at every batch size on the bench host (see
# module docstring; BENCH_r12.json records the commit-path numbers).
_USE_LIMBS = os.environ.get("BCP_MUHASH_LIMBS") == "1"


def batch_product(values: list[int]) -> int:
    """prod(values) mod p. Dispatches to the measured-faster python-int
    path unless BCP_MUHASH_LIMBS=1 forces the numpy limb backend (which
    also needs numpy present and a non-tiny batch)."""
    if _USE_LIMBS and _np is not None and len(values) >= 8:
        return _batch_product_limbs(values)
    return batch_product_ref(values)


class MuHash:
    """The incremental accumulator one store shard maintains.

    State is a single group element (identity 1 = empty set), serialized
    as 384 big-endian bytes in the shard's meta row. `apply` consumes one
    commit's delta: added/removed elements are tree-multiplied in batch
    and the removals cost exactly one modular inverse."""

    def __init__(self, state: int = 1):
        self.state = state % MUHASH_P

    @classmethod
    def from_bytes(cls, raw: Optional[bytes]) -> "MuHash":
        if not raw:
            return cls(1)
        return cls(int.from_bytes(raw, "big"))

    def to_bytes(self) -> bytes:
        return self.state.to_bytes(384, "big")

    def insert(self, data: bytes) -> None:
        self.state = (self.state * element(data)) % MUHASH_P

    def remove(self, data: bytes) -> None:
        self.state = (self.state * pow(element(data), -1, MUHASH_P)) % MUHASH_P

    def apply(self, added: list[int], removed: list[int]) -> None:
        """Batch delta: state *= prod(added) / prod(removed)."""
        if added:
            self.state = (self.state * batch_product(added)) % MUHASH_P
        if removed:
            inv = pow(batch_product(removed), -1, MUHASH_P)
            self.state = (self.state * inv) % MUHASH_P

    def digest(self) -> bytes:
        return digest_of(self.state)


def combine(states: Iterable[int]) -> int:
    """Global accumulator of a sharded store: the product of the per-shard
    states. Partition-independent — any shard count yields one digest."""
    return batch_product_ref(states)
