"""Block and undo storage.

Reference: blk?????.dat / rev?????.dat append-only files with
(netmagic, size) framing (src/validation.cpp SaveBlockToDisk,
WriteBlockToDisk, UndoWriteToDisk), positions tracked in the block index
(CDiskBlockPos). Same design here: append-only .dat files + an in-memory
position map persisted via BlockIndexDB. Append+flush ordering before index
update is the crash-safety contract (SURVEY.md §6.3).
"""

from __future__ import annotations

import os
import struct
from typing import Optional

MAX_BLOCKFILE_SIZE = 128 * 1024 * 1024  # 0x8000000 (MAX_BLOCKFILE_SIZE)


class MemoryBlockStore:
    """Dict-backed store for tests / ephemeral regtest nodes."""

    def __init__(self):
        self._blocks: dict[bytes, bytes] = {}
        self._undo: dict[bytes, bytes] = {}

    def put_block(self, h: bytes, raw: bytes) -> None:
        self._blocks[h] = raw

    def get_block(self, h: bytes) -> Optional[bytes]:
        return self._blocks.get(h)

    def have_block(self, h: bytes) -> bool:
        return h in self._blocks

    def put_undo(self, h: bytes, raw: bytes) -> None:
        self._undo[h] = raw

    def get_undo(self, h: bytes) -> Optional[bytes]:
        return self._undo.get(h)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class BlockStore:
    """File-backed store: blocks/blk?????.dat + rev?????.dat with
    (netmagic, u32 size) record framing, exactly the reference's on-disk
    layout. Positions are kept in memory and re-persisted by the caller
    (BlockIndexDB) — a restart reloads them from the index DB."""

    def __init__(self, datadir: str, netmagic: bytes,
                 max_file_size: int = MAX_BLOCKFILE_SIZE):
        self.dir = os.path.join(datadir, "blocks")
        os.makedirs(self.dir, exist_ok=True)
        self.netmagic = netmagic
        self.max_file_size = max_file_size
        self.positions: dict[bytes, tuple[int, int, int]] = {}  # h -> (file, offset, size)
        self.undo_positions: dict[bytes, tuple[int, int, int]] = {}
        self._files: dict[tuple[str, int], object] = {}
        self._cur_file = self._scan_last_file("blk")

    def _scan_last_file(self, prefix: str) -> int:
        n = 0
        while os.path.exists(self._path(prefix, n + 1)):
            n += 1
        return n

    def _path(self, prefix: str, n: int) -> str:
        return os.path.join(self.dir, f"{prefix}{n:05d}.dat")

    def _open(self, prefix: str, n: int):
        key = (prefix, n)
        f = self._files.get(key)
        if f is None:
            f = open(self._path(prefix, n), "a+b")
            self._files[key] = f
        return f

    def _append_to(self, prefix: str, n: int, raw: bytes) -> tuple[int, int, int]:
        """Append one (netmagic, size, raw) record to {prefix}{n}.dat."""
        f = self._open(prefix, n)
        f.seek(0, os.SEEK_END)
        record = self.netmagic + struct.pack("<I", len(raw)) + raw
        offset = f.tell() + 8  # data starts after magic+size
        f.write(record)
        return n, offset, len(raw)

    def _append(self, prefix: str, cur_attr: str, raw: bytes) -> tuple[int, int, int]:
        n = getattr(self, cur_attr)
        f = self._open(prefix, n)
        f.seek(0, os.SEEK_END)
        if f.tell() + len(raw) + 8 > self.max_file_size and f.tell() > 0:
            n += 1
            setattr(self, cur_attr, n)
        return self._append_to(prefix, n, raw)

    def _read(self, prefix: str, pos: tuple[int, int, int]) -> bytes:
        n, offset, size = pos
        f = self._open(prefix, n)
        f.seek(offset)
        return f.read(size)

    # -- public interface (shared with MemoryBlockStore) --

    def put_block(self, h: bytes, raw: bytes) -> None:
        if h in self.positions:
            return
        self.positions[h] = self._append("blk", "_cur_file", raw)

    def get_block(self, h: bytes) -> Optional[bytes]:
        pos = self.positions.get(h)
        return self._read("blk", pos) if pos else None

    def have_block(self, h: bytes) -> bool:
        return h in self.positions

    def put_undo(self, h: bytes, raw: bytes) -> None:
        if h in self.undo_positions:
            return
        # undo lives in the rev file PAIRED with the block's blk file
        # (UndoWriteToDisk uses the block's nFile) — pruning blk{n}+rev{n}
        # as a unit then can't orphan undo data of unpruned blocks
        blockpos = self.positions.get(h)
        n = blockpos[0] if blockpos is not None else self._cur_file
        self.undo_positions[h] = self._append_to("rev", n, raw)

    def get_undo(self, h: bytes) -> Optional[bytes]:
        pos = self.undo_positions.get(h)
        return self._read("rev", pos) if pos else None

    def flush(self) -> None:
        """fsync data files BEFORE the index/chainstate batch commits —
        the reference's FlushBlockFile ordering."""
        for f in self._files.values():
            f.flush()
            os.fsync(f.fileno())

    # -- pruning (UnlinkPrunedFiles, src/validation.cpp) -----------------

    def blocks_in_file(self, n: int) -> list[bytes]:
        return [h for h, pos in self.positions.items() if pos[0] == n]

    def file_usage(self) -> int:
        """Total bytes across all blk/rev files (CalculateCurrentUsage)."""
        total = 0
        for prefix in ("blk", "rev"):
            i = 0
            while True:
                path = self._path(prefix, i)
                if not os.path.exists(path):
                    break
                total += os.path.getsize(path)
                i += 1
        return total

    def prune_file(self, n: int) -> list[bytes]:
        """Delete blk{n} (and rev{n} when safe) and forget the pruned
        blocks' positions. Returns the block hashes whose data was removed
        (caller clears index status). The current append file is never
        pruned."""
        if n >= self._cur_file:
            return []
        removed = set(self.blocks_in_file(n))
        truncate = ["blk"]
        # rev{n} normally holds exactly file-n blocks' undo (put_undo pairs
        # them), but a pre-pairing datadir can have foreign undo records in
        # it — truncating then would orphan undo of unpruned blocks, so
        # only the positions of pruned blocks are dropped in that case
        undo_in_rev_n = {h for h, p in self.undo_positions.items()
                         if p[0] == n}
        if undo_in_rev_n <= removed:
            truncate.append("rev")
        for prefix in truncate:
            f = self._files.pop((prefix, n), None)
            if f is not None:
                f.close()
            path = self._path(prefix, n)
            if os.path.exists(path):
                # truncate-in-place rather than unlink: _scan_last_file
                # relies on contiguous file numbering at startup
                with open(path, "wb"):
                    pass
        self.positions = {h: p for h, p in self.positions.items()
                          if h not in removed}
        self.undo_positions = {h: p for h, p in self.undo_positions.items()
                               if h not in removed}
        return list(removed)

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()
