"""Proof-carrying snapshot certificates (ISSUE 17).

PR 12's ``-assumeutxo`` made an operator-supplied digest the single
trust anchor of snapshot onboarding, and PR 16 multiplied the blast
radius: one forged snapshot poisons every replica bootstrapped from it,
undetected until hours of shadow re-validation complete. Following
PAPERS.md 2407.03511 (scalable proofs for verifying cryptographic
hashing in blockchain) this module ships each snapshot with a succinct,
recursively-committed SHA-256 certificate — no SNARK — binding three
things together:

  (a) a Merkle-mountain-range commitment over the header chain
      genesis..H (leaf = block hash; peaks follow the pow2 decomposition
      of the leaf count; the root bags peaks right-to-left). Levels are
      hashed lane-parallel on the batched SHA-256 tree machinery
      (ops/merkle.sha256d_pairs), so verification is a handful of
      batched tree recomputations;
  (b) a per-epoch MuHash3072 digest trajectory: the UTXO-set digest
      after block E, 2E, ... and finally H. The dumping validator
      rebuilds it EXACTLY from its undo data by walking blocks tip->1
      and dividing out each block's delta (the accumulator group is
      abelian — one modular inverse per checkpoint, not per block);
  (c) a commitment chain c_0 = H(tag || mmr_root || H || E),
      c_i = H(c_{i-1} || height_i || digest_i) sealing the trajectory
      order and binding it to the header commitment; the final link
      covers the snapshot's set digest itself.

Verification at load (seconds, before a single row is served): recompute
the MMR root from the snapshot's own PoW-checked headers, recompute the
commitment chain, and require the final trajectory digest to equal the
manifest digest the row stream is checked against. A wrong MMR root,
truncated trajectory, or bit-flipped certificate is rejected outright —
the wipe-and-reject path, same as a wrong set digest today. A forged
EPOCH (internally consistent certificate, wrong history) survives load
but is caught by the background shadow validator at the first divergent
epoch checkpoint — O(E) blocks instead of O(H) — which hard-aborts
immediately. ``sample_epochs`` powers ``-snapshotspotcheck=K``: a seeded
draw of K certificate-committed epochs that get full script
re-validation while the rest replay cheaply, turning replica onboarding
from hours into minutes.

The ``snapshot_cert`` fault site (util/faults, explicit-only) arms both
legs: fail-* at verify proves wipe-and-reject, poison-output at build
forges one mid-trajectory epoch digest before the chain is sealed.

stdlib + the batched hashing helper only — importable from jax-free
contexts (sha256d_pairs lazily imports the device path and degrades to
the host loop).
"""

from __future__ import annotations

import hashlib
import random
import struct
from typing import Iterable, Optional

from ..util.faults import INJECTOR, SNAPSHOT_CERT_SITE
from ..util.log import log_printf
from . import muhash

CERT_VERSION = 1
CERT_NAME = "CERTIFICATE.json"
DEFAULT_EPOCH_BLOCKS = 64
_CHAIN_TAG = b"BCP-SNAPCERT-v1"


class CertificateError(Exception):
    """A snapshot certificate that failed structural verification."""


def _sha256d(b: bytes) -> bytes:
    return hashlib.sha256(hashlib.sha256(b).digest()).digest()


def _hash_level(pairs: list[bytes]) -> list[bytes]:
    """One MMR level: sha256d over 64-byte concatenations, batched on the
    device when the level is wide enough to pay for the trip."""
    try:
        from ..ops.merkle import sha256d_pairs
        return sha256d_pairs(pairs)
    except ImportError:  # pragma: no cover - jax-free caller
        return [_sha256d(p) for p in pairs]


# -- Merkle mountain range --------------------------------------------------

def mmr_peaks(leaves: list[bytes]) -> list[bytes]:
    """The MMR peak list of ``leaves``: one perfect-binary-tree root per
    set bit of len(leaves), largest tree first — exactly the peak
    structure sequential MMR appends produce. Each tree reduces level by
    level through the batched pair hasher."""
    peaks = []
    pos = 0
    n = len(leaves)
    for bit in range(n.bit_length() - 1, -1, -1):
        size = 1 << bit
        if not n & size:
            continue
        level = leaves[pos:pos + size]
        pos += size
        while len(level) > 1:
            level = _hash_level(
                [level[i] + level[i + 1] for i in range(0, len(level), 2)])
        peaks.append(level[0])
    return peaks


def mmr_root(leaves: list[bytes]) -> bytes:
    """Bag the peaks right-to-left (acc = H(peak || acc)) into one root.
    Empty input is a caller bug — a snapshot always has genesis."""
    peaks = mmr_peaks(leaves)
    if not peaks:
        raise CertificateError("MMR over zero leaves")
    acc = peaks[-1]
    for peak in reversed(peaks[:-1]):
        acc = _sha256d(peak + acc)
    return acc


# -- epoch trajectory -------------------------------------------------------

def checkpoint_heights(height: int, epoch_blocks: int) -> list[int]:
    """The certificate's committed checkpoint heights: every multiple of
    E up to H, plus the tail checkpoint H itself when H % E != 0. Always
    non-empty and always ending at H."""
    if height < 1 or epoch_blocks < 1:
        raise CertificateError(
            f"bad trajectory shape: height={height} E={epoch_blocks}")
    hs = list(range(epoch_blocks, height + 1, epoch_blocks))
    if not hs or hs[-1] != height:
        hs.append(height)
    return hs


def epoch_trajectory(final_state: int, deltas: Iterable[tuple],
                     height: int, epoch_blocks: int) -> list[dict]:
    """Rebuild the per-epoch digest trajectory from the final accumulator
    state by walking block deltas tip->1.

    ``deltas`` yields ``(h, created, spent)`` for h = height..1 in strictly
    descending order, where created/spent are lists of ``(key36, coin_ser)``
    rows exactly as the store persists them (undo data supplies the spent
    side). Because the accumulator group is abelian, the state AT any
    checkpoint c equals final_state * prod(spent above c) / prod(created
    above c) — the division costs one modular inverse per checkpoint.
    Returns ascending ``[{"height": h, "muhash": hex}, ...]`` ending at
    ``height`` with the digest of ``final_state`` itself."""
    targets = checkpoint_heights(height, epoch_blocks)
    out = [{"height": height,
            "muhash": muhash.digest_of(final_state).hex()}]
    remaining = [h for h in targets if h != height]
    if not remaining:
        return out
    lowest = remaining[0]
    num = 1  # product of spent elements above the current height
    den = 1  # product of created elements above the current height
    expect = height
    for h, created, spent in deltas:
        if h != expect:
            raise CertificateError(
                f"delta walk out of order: got height {h}, want {expect}")
        expect -= 1
        if created:
            den = den * muhash.batch_product(
                [muhash.coin_element(k, s) for k, s in created]
            ) % muhash.MUHASH_P
        if spent:
            num = num * muhash.batch_product(
                [muhash.coin_element(k, s) for k, s in spent]
            ) % muhash.MUHASH_P
        if h - 1 == remaining[-1]:
            state = (final_state * num % muhash.MUHASH_P
                     * pow(den, -1, muhash.MUHASH_P)) % muhash.MUHASH_P
            out.append({"height": h - 1,
                        "muhash": muhash.digest_of(state).hex()})
            remaining.pop()
            if not remaining:
                break
        if h - 1 < lowest:
            break
    if remaining:
        raise CertificateError(
            f"delta walk ended before checkpoints {remaining}")
    out.reverse()
    return out


# -- commitment chain -------------------------------------------------------

def commitment_chain(root: bytes, height: int, epoch_blocks: int,
                     epochs: list[dict]) -> bytes:
    """c_0 = H(tag || mmr_root || LE64(H) || LE32(E)); each checkpoint
    then links c_i = H(c_{i-1} || LE64(h_i) || digest_i). The final link
    covers the snapshot set digest, so the chain binds headers ->
    trajectory -> final digest as one recursively-committed value."""
    c = _sha256d(_CHAIN_TAG + root + struct.pack("<QI", height, epoch_blocks))
    for ep in epochs:
        c = _sha256d(c + struct.pack("<Q", int(ep["height"]))
                     + bytes.fromhex(ep["muhash"]))
    return c


# -- build / verify ---------------------------------------------------------

def build_certificate(header_hashes: list[bytes], height: int,
                      epoch_blocks: int, final_state: int,
                      deltas: Iterable[tuple]) -> dict:
    """Produce the certificate dict at dumptxoutset time.

    ``header_hashes`` are the block hashes genesis..H in height order
    (len == H+1); ``deltas`` feeds :func:`epoch_trajectory`. The armed
    ``snapshot_cert`` poison hook forges one mid-trajectory epoch digest
    BEFORE the commitment chain is sealed — the internally-consistent
    forgery the epoch-divergence drills must catch."""
    if len(header_hashes) != height + 1:
        raise CertificateError(
            f"{len(header_hashes)} header hashes for height {height}")
    epochs = epoch_trajectory(final_state, deltas, height, epoch_blocks)
    if INJECTOR.should_poison(SNAPSHOT_CERT_SITE) and len(epochs) >= 2:
        forge = epochs[(len(epochs) - 1) // 2]
        raw = bytearray(bytes.fromhex(forge["muhash"]))
        raw[0] ^= 0x01
        forge["muhash"] = bytes(raw).hex()
        log_printf("snapshot_cert: POISONED epoch %d digest (drill)",
                   forge["height"])
    root = mmr_root(header_hashes)
    return {
        "version": CERT_VERSION,
        "height": height,
        "headers": height + 1,
        "epoch_blocks": epoch_blocks,
        "mmr_root": root.hex(),
        "epochs": epochs,
        "commitment": commitment_chain(
            root, height, epoch_blocks, epochs).hex(),
    }


def verify_certificate(cert: dict, header_hashes: list[bytes],
                       height: int, set_digest_hex: str) -> dict:
    """Structural verification at loadtxoutset, BEFORE any row is
    streamed: recompute the MMR root over the snapshot's own headers,
    require complete ascending epoch coverage, recompute the commitment
    chain, and require the final trajectory digest to equal the manifest
    set digest. Raises CertificateError on any mismatch (the caller takes
    the wipe-and-reject path). Returns ``{height: digest_hex}`` — the
    checkpoint map the background shadow validator checks itself against
    as it replays history."""
    INJECTOR.on_call(SNAPSHOT_CERT_SITE)
    if not isinstance(cert, dict) or cert.get("version") != CERT_VERSION:
        raise CertificateError("missing or unknown certificate version")
    if int(cert.get("height", -1)) != height:
        raise CertificateError(
            f"certificate height {cert.get('height')} != snapshot {height}")
    if int(cert.get("headers", -1)) != len(header_hashes) or \
            len(header_hashes) != height + 1:
        raise CertificateError("certificate header count mismatch")
    epoch_blocks = int(cert.get("epoch_blocks", 0))
    epochs = cert.get("epochs") or []
    try:
        want_heights = checkpoint_heights(height, epoch_blocks)
    except CertificateError:
        raise CertificateError(
            f"certificate epoch stride {epoch_blocks} invalid") from None
    got_heights = [int(ep.get("height", -1)) for ep in epochs]
    if got_heights != want_heights:
        raise CertificateError(
            "certificate epoch trajectory is truncated or misaligned "
            f"(got {len(got_heights)} checkpoints, want {len(want_heights)})")
    for ep in epochs:
        if len(bytes.fromhex(ep.get("muhash", ""))) != 32:
            raise CertificateError("malformed epoch digest")
    if epochs[-1]["muhash"] != set_digest_hex:
        raise CertificateError(
            "certificate final digest does not cover the snapshot digest")
    root = mmr_root(header_hashes)
    if root.hex() != cert.get("mmr_root"):
        raise CertificateError(
            "certificate MMR root does not match the snapshot headers")
    want_c = commitment_chain(root, height, epoch_blocks, epochs)
    if want_c.hex() != cert.get("commitment"):
        raise CertificateError("certificate commitment chain broken")
    return {int(ep["height"]): ep["muhash"] for ep in epochs}


# -- spot-check sampling ----------------------------------------------------

def sample_epochs(cert_epochs: list[int], k: int,
                  seed: Optional[int] = None) -> list[int]:
    """Seeded draw of ``k`` certificate-committed checkpoint heights for
    ``-snapshotspotcheck``. The FINAL checkpoint is always included (the
    whole-set digest equality is never sampled away); the remaining k-1
    come from a deterministic shuffle of the earlier checkpoints, so one
    seed replays the identical drill. k >= len(cert_epochs) degrades to
    full coverage."""
    if not cert_epochs:
        return []
    heights = sorted(cert_epochs)
    final = heights[-1]
    rest = heights[:-1]
    if k >= len(heights):
        return heights
    rng = random.Random(seed)
    rng.shuffle(rest)
    return sorted(rest[:max(0, k - 1)] + [final])
