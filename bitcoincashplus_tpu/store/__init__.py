"""Persistence layer.

Reference mapping (SURVEY.md §3.1):
  - blockstore.py — blk?????.dat / rev?????.dat append-only block & undo
    files (src/validation.cpp SaveBlockToDisk / WriteUndoDataForBlock).
  - kvstore.py — CDBWrapper-shaped ordered KV (src/dbwrapper.{h,cpp}) over
    sqlite3 (stdlib; LevelDB has no binding in this environment — deviation
    documented in SURVEY.md §8.5.6). Batch-atomic writes + WAL mode give the
    same crash-safety contract (flush cadence + best-block marker).
  - chainstatedb.py — the coins DB ('chainstate') and block index DB
    (src/txdb.{h,cpp} CCoinsViewDB / CBlockTreeDB) on top of kvstore.
  - sharded.py — ShardedCoinsDB: N hash-partitioned coins backends behind
    one CoinsView facade (parallel journaled flush, cross-shard epoch
    manifest, incremental MuHash set accumulator).
  - muhash.py — the multiplicative UTXO-set hash (MuHash3072-shaped;
    numpy limb batch products) shards and snapshots are committed to.
  - snapshot.py — dumptxoutset/loadtxoutset serialization (per-shard
    streams + digest-stamped manifest, the assumeutxo onboarding format).
"""

from .blockstore import BlockStore, MemoryBlockStore
from .kvstore import KVStore
from .chainstatedb import CoinsDB, BlockIndexDB
from .sharded import ShardedCoinsDB

__all__ = ["BlockStore", "MemoryBlockStore", "KVStore", "CoinsDB",
           "BlockIndexDB", "ShardedCoinsDB"]
