"""Persistence layer.

Reference mapping (SURVEY.md §3.1):
  - blockstore.py — blk?????.dat / rev?????.dat append-only block & undo
    files (src/validation.cpp SaveBlockToDisk / WriteUndoDataForBlock).
  - kvstore.py — CDBWrapper-shaped ordered KV (src/dbwrapper.{h,cpp}) over
    sqlite3 (stdlib; LevelDB has no binding in this environment — deviation
    documented in SURVEY.md §8.5.6). Batch-atomic writes + WAL mode give the
    same crash-safety contract (flush cadence + best-block marker).
  - chainstatedb.py — the coins DB ('chainstate') and block index DB
    (src/txdb.{h,cpp} CCoinsViewDB / CBlockTreeDB) on top of kvstore.
"""

from .blockstore import BlockStore, MemoryBlockStore
from .kvstore import KVStore
from .chainstatedb import CoinsDB, BlockIndexDB

__all__ = ["BlockStore", "MemoryBlockStore", "KVStore", "CoinsDB", "BlockIndexDB"]
