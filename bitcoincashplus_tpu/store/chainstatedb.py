"""Coins DB and block-index DB over the KV store.

Reference: src/txdb.{h,cpp} — CCoinsViewDB ('chainstate' LevelDB: key
DB_COIN 'C' + outpoint, value Coin; DB_BEST_BLOCK 'B' marker) and
CBlockTreeDB ('blocks/index': DB_BLOCK_INDEX 'b' + hash -> CDiskBlockIndex,
DB_BLOCK_FILES, DB_REINDEX_FLAG, DB_FLAG for -txindex).

The coins schema here stores one row per outpoint (the 0.15+ per-output
model, not 0.14's per-tx CCoins) — better granularity for flush batching.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, Optional

from ..consensus.block import CBlockHeader
from ..consensus.serialize import ByteReader
from ..consensus.tx import COutPoint
from ..util.faults import maybe_crash
from ..util.log import log_printf
from ..validation.coins import Coin, CoinsView
from .kvstore import KVStore, atomic_write_bytes

_COIN = b"C"
_BEST = b"B"
_BLOCK_INDEX = b"b"
_BLOCK_POS = b"f"
_UNDO_POS = b"u"
_FLAG = b"F"
_NULL_HASH = b"\x00" * 32


def _coin_key(op: COutPoint) -> bytes:
    return _COIN + op.hash + struct.pack("<I", op.n)


# ---------------------------------------------------------------------------
# Commit journal — the crash-safety layer for block connect/disconnect.
#
# Every coins batch (spends + creates + best-block marker) is first made
# durable as a self-checksummed journal file (fsync-before-rename,
# kvstore.atomic_write_bytes), then applied to sqlite, then the journal is
# cleared. On startup (ChainstateManager.__init__ -> recover_journal):
#   - valid journal present  -> the crash hit between durability and clear:
#     REPLAY the batch (puts/deletes are idempotent) -> post-block state;
#   - torn/absent journal    -> the crash hit before durability: discard the
#     fragment (ROLLBACK)    -> pre-block state, sqlite untouched or its
#     uncommitted transaction self-discarded by WAL recovery.
# Either way the reopened UTXO set is exactly pre- or post-block, never a
# torn mix — verified by the crash-injection tests killing the process at
# every step (tests/unit/test_crashsafe_store.py).
# ---------------------------------------------------------------------------

_JOURNAL_MAGIC = b"BCPJ1"


def _encode_journal(puts: dict[bytes, bytes], deletes: list[bytes]) -> bytes:
    body = [struct.pack("<I", len(puts))]
    for k, v in puts.items():
        body.append(struct.pack("<I", len(k)) + k)
        body.append(struct.pack("<I", len(v)) + v)
    body.append(struct.pack("<I", len(deletes)))
    for k in deletes:
        body.append(struct.pack("<I", len(k)) + k)
    blob = b"".join(body)
    return _JOURNAL_MAGIC + struct.pack("<I", zlib.crc32(blob)) + blob


def _decode_journal(data: bytes):
    """(puts, deletes) or None for anything torn/corrupt (short file, bad
    magic, bad checksum, truncated record)."""
    if len(data) < 9 or data[:5] != _JOURNAL_MAGIC:
        return None
    (crc,) = struct.unpack_from("<I", data, 5)
    blob = data[9:]
    if zlib.crc32(blob) != crc:
        return None
    try:
        pos = 0

        def take(n: int) -> bytes:
            nonlocal pos
            if pos + n > len(blob):
                raise ValueError("truncated journal")
            out = blob[pos:pos + n]
            pos += n
            return out

        (n_puts,) = struct.unpack("<I", take(4))
        puts: dict[bytes, bytes] = {}
        for _ in range(n_puts):
            (klen,) = struct.unpack("<I", take(4))
            k = take(klen)
            (vlen,) = struct.unpack("<I", take(4))
            puts[k] = take(vlen)
        (n_dels,) = struct.unpack("<I", take(4))
        deletes = []
        for _ in range(n_dels):
            (klen,) = struct.unpack("<I", take(4))
            deletes.append(take(klen))
        return puts, deletes
    except (ValueError, struct.error):
        return None


class CoinsDB(CoinsView):
    """CCoinsViewDB — the persistent bottom of the view stack.

    With ``journal_path`` set, every batch commit is journaled (see the
    commit-journal block above) so a crash mid-commit can always be
    resolved to a whole pre- or post-batch state at reopen."""

    def __init__(self, kv: KVStore, journal_path: Optional[str] = None):
        self.kv = kv
        self.journal_path = journal_path

    def _commit(self, puts: dict[bytes, bytes], deletes: list[bytes]) -> None:
        """The journaled write path shared by batch_write and
        batch_write_serialized. Step order IS the crash-safety contract:
        (1) journal durable, (2) DB apply, (3) journal clear."""
        if self.journal_path is not None:
            atomic_write_bytes(self.journal_path,
                              _encode_journal(puts, deletes))
            maybe_crash("journal:durable")
        self.kv.write_batch(puts, deletes, sync=True)
        maybe_crash("journal:pre-clear")
        if self.journal_path is not None and os.path.exists(self.journal_path):
            os.unlink(self.journal_path)

    def recover_journal(self) -> bool:
        """Startup replay/rollback (called by ChainstateManager.__init__
        before any chainstate read). Returns True when a valid journal was
        replayed. Replay is idempotent — a journal that was already fully
        applied before the crash re-applies to the same state."""
        if self.journal_path is None:
            return False
        stale_tmp = self.journal_path + ".tmp"
        if os.path.exists(stale_tmp):
            os.unlink(stale_tmp)  # pre-durability fragment: rollback
        if not os.path.exists(self.journal_path):
            return False
        with open(self.journal_path, "rb") as f:
            data = f.read()
        decoded = _decode_journal(data)
        if decoded is None:
            # torn journal: the commit never reached durability — the DB
            # still holds the whole pre-batch state; discard the fragment
            log_printf("chainstate journal torn — rolled back to the "
                       "pre-commit state")
            os.unlink(self.journal_path)
            return False
        puts, deletes = decoded
        self.kv.write_batch(puts, deletes, sync=True)
        os.unlink(self.journal_path)
        log_printf("chainstate journal replayed: %d put(s), %d delete(s)",
                   len(puts), len(deletes))
        return True

    def get_coin(self, outpoint: COutPoint) -> Optional[Coin]:
        raw = self.kv.get(_coin_key(outpoint))
        return Coin.deserialize(raw) if raw is not None else None

    def have_coin(self, outpoint: COutPoint) -> bool:
        """Existence probe without value fetch/deserialize — the BIP30
        pre-scan's per-output fast path (CoinsCache.have_coin)."""
        return self.kv.exists(_coin_key(outpoint))

    def best_block(self) -> bytes:
        return self.kv.get(_BEST) or _NULL_HASH

    def batch_write(self, coins: dict, best_block: bytes) -> None:
        puts: dict[bytes, bytes] = {}
        deletes: list[bytes] = []
        for op, coin in coins.items():
            if coin is None:
                deletes.append(_coin_key(op))
            else:
                puts[_coin_key(op)] = coin.serialize()
        puts[_BEST] = best_block
        # single transaction: coins + best-block marker move together —
        # the crash-consistency invariant (SURVEY.md §6.3); journaled when
        # a journal path is configured (crash at any step -> pre or post)
        self._commit(puts, deletes)

    def count_coins(self) -> int:
        return sum(1 for _ in self.kv.iterate(_COIN))

    def iterate_coins(self) -> Iterator[tuple[bytes, bytes]]:
        """(key36, coin_ser) rows — the facade-uniform iteration surface
        shared with ShardedCoinsDB (gettxoutsetinfo, snapshot dump)."""
        for k, v in self.kv.iterate(_COIN):
            yield k[1:], v

    # -- raw-key entry points for the native connect engine --------------
    # (native/connect.cpp speaks 36-byte outpoint keys + Coin.serialize
    # blobs; these avoid a COutPoint/Coin object round trip per row)

    def get_serialized_many(self, keys36: list[bytes]) -> dict[bytes, bytes]:
        """{key36: coin_serialization} for present rows (miss servicing)."""
        rows = self.kv.get_many([_COIN + k for k in keys36])
        return {k[1:]: v for k, v in rows.items()}

    def batch_write_serialized(self, entries, best_block: bytes) -> None:
        """entries: iterable of (key36, coin_ser | None-for-delete); one
        transaction with the best-block marker, same crash-consistency
        unit as batch_write."""
        puts: dict[bytes, bytes] = {}
        deletes: list[bytes] = []
        for k, ser in entries:
            if ser is None:
                deletes.append(_COIN + k)
            else:
                puts[_COIN + k] = ser
        puts[_BEST] = best_block
        self._commit(puts, deletes)


class BlockIndexDB:
    """CBlockTreeDB — headers + file positions + flags, enough to rebuild
    the in-memory block tree at startup (LoadBlockIndexDB)."""

    def __init__(self, kv: KVStore):
        self.kv = kv

    def put_index_batch(self, entries: list) -> None:
        """entries: (hash, header80, height, status, n_tx, blkpos, undopos)."""
        puts = {}
        for h, header80, height, status, n_tx, blkpos, undopos in entries:
            puts[_BLOCK_INDEX + h] = (
                header80
                + struct.pack("<iII", height, status, n_tx)
                + struct.pack("<iii", *(blkpos or (-1, -1, -1)))
                + struct.pack("<iii", *(undopos or (-1, -1, -1)))
            )
        self.kv.write_batch(puts)

    def iterate_index(self) -> Iterator[tuple]:
        """Yields (hash, CBlockHeader, height, status, n_tx, blkpos, undopos)."""
        for k, v in self.kv.iterate(_BLOCK_INDEX):
            h = k[1:]
            header = CBlockHeader.deserialize(ByteReader(v[:80]))
            height, status, n_tx = struct.unpack("<iII", v[80:92])
            blkpos = struct.unpack("<iii", v[92:104])
            undopos = struct.unpack("<iii", v[104:116])
            yield (
                h,
                header,
                height,
                status,
                n_tx,
                None if blkpos[0] < 0 else blkpos,
                None if undopos[0] < 0 else undopos,
            )

    def put_flag(self, name: bytes, value: bool) -> None:
        self.kv.put(_FLAG + name, b"1" if value else b"0")

    def get_flag(self, name: bytes) -> bool:
        return self.kv.get(_FLAG + name) == b"1"
