"""Coins DB and block-index DB over the KV store.

Reference: src/txdb.{h,cpp} — CCoinsViewDB ('chainstate' LevelDB: key
DB_COIN 'C' + outpoint, value Coin; DB_BEST_BLOCK 'B' marker) and
CBlockTreeDB ('blocks/index': DB_BLOCK_INDEX 'b' + hash -> CDiskBlockIndex,
DB_BLOCK_FILES, DB_REINDEX_FLAG, DB_FLAG for -txindex).

The coins schema here stores one row per outpoint (the 0.15+ per-output
model, not 0.14's per-tx CCoins) — better granularity for flush batching.
"""

from __future__ import annotations

import struct
from typing import Iterator, Optional

from ..consensus.block import CBlockHeader
from ..consensus.serialize import ByteReader
from ..consensus.tx import COutPoint
from ..validation.coins import Coin, CoinsView
from .kvstore import KVStore

_COIN = b"C"
_BEST = b"B"
_BLOCK_INDEX = b"b"
_BLOCK_POS = b"f"
_UNDO_POS = b"u"
_FLAG = b"F"
_NULL_HASH = b"\x00" * 32


def _coin_key(op: COutPoint) -> bytes:
    return _COIN + op.hash + struct.pack("<I", op.n)


class CoinsDB(CoinsView):
    """CCoinsViewDB — the persistent bottom of the view stack."""

    def __init__(self, kv: KVStore):
        self.kv = kv

    def get_coin(self, outpoint: COutPoint) -> Optional[Coin]:
        raw = self.kv.get(_coin_key(outpoint))
        return Coin.deserialize(raw) if raw is not None else None

    def best_block(self) -> bytes:
        return self.kv.get(_BEST) or _NULL_HASH

    def batch_write(self, coins: dict, best_block: bytes) -> None:
        puts: dict[bytes, bytes] = {}
        deletes: list[bytes] = []
        for op, coin in coins.items():
            if coin is None:
                deletes.append(_coin_key(op))
            else:
                puts[_coin_key(op)] = coin.serialize()
        puts[_BEST] = best_block
        # single transaction: coins + best-block marker move together —
        # the crash-consistency invariant (SURVEY.md §6.3)
        self.kv.write_batch(puts, deletes, sync=True)

    def count_coins(self) -> int:
        return sum(1 for _ in self.kv.iterate(_COIN))

    # -- raw-key entry points for the native connect engine --------------
    # (native/connect.cpp speaks 36-byte outpoint keys + Coin.serialize
    # blobs; these avoid a COutPoint/Coin object round trip per row)

    def get_serialized_many(self, keys36: list[bytes]) -> dict[bytes, bytes]:
        """{key36: coin_serialization} for present rows (miss servicing)."""
        rows = self.kv.get_many([_COIN + k for k in keys36])
        return {k[1:]: v for k, v in rows.items()}

    def batch_write_serialized(self, entries, best_block: bytes) -> None:
        """entries: iterable of (key36, coin_ser | None-for-delete); one
        transaction with the best-block marker, same crash-consistency
        unit as batch_write."""
        puts: dict[bytes, bytes] = {}
        deletes: list[bytes] = []
        for k, ser in entries:
            if ser is None:
                deletes.append(_COIN + k)
            else:
                puts[_COIN + k] = ser
        puts[_BEST] = best_block
        self.kv.write_batch(puts, deletes, sync=True)


class BlockIndexDB:
    """CBlockTreeDB — headers + file positions + flags, enough to rebuild
    the in-memory block tree at startup (LoadBlockIndexDB)."""

    def __init__(self, kv: KVStore):
        self.kv = kv

    def put_index_batch(self, entries: list) -> None:
        """entries: (hash, header80, height, status, n_tx, blkpos, undopos)."""
        puts = {}
        for h, header80, height, status, n_tx, blkpos, undopos in entries:
            puts[_BLOCK_INDEX + h] = (
                header80
                + struct.pack("<iII", height, status, n_tx)
                + struct.pack("<iii", *(blkpos or (-1, -1, -1)))
                + struct.pack("<iii", *(undopos or (-1, -1, -1)))
            )
        self.kv.write_batch(puts)

    def iterate_index(self) -> Iterator[tuple]:
        """Yields (hash, CBlockHeader, height, status, n_tx, blkpos, undopos)."""
        for k, v in self.kv.iterate(_BLOCK_INDEX):
            h = k[1:]
            header = CBlockHeader.deserialize(ByteReader(v[:80]))
            height, status, n_tx = struct.unpack("<iII", v[80:92])
            blkpos = struct.unpack("<iii", v[92:104])
            undopos = struct.unpack("<iii", v[104:116])
            yield (
                h,
                header,
                height,
                status,
                n_tx,
                None if blkpos[0] < 0 else blkpos,
                None if undopos[0] < 0 else undopos,
            )

    def put_flag(self, name: bytes, value: bool) -> None:
        self.kv.put(_FLAG + name, b"1" if value else b"0")

    def get_flag(self, name: bytes) -> bool:
        return self.kv.get(_FLAG + name) == b"1"
