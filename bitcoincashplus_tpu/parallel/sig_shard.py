"""Multi-chip ECDSA batch sharding (P1 in SURVEY.md §3.2).

The signature-batch axis is embarrassingly parallel: shard the B lanes of
the PRODUCTION w=4 windowed Pallas pipeline (ops/secp256k1._w4_bytes_program
— the same kernel behind bench config 4) across the ('chip',) mesh with
shard_map. Inputs are the byte matrices ((B, 32) uint8 per field) sharded on
the batch axis; each chip expands its shard to window planes / 13-bit limbs
on device and runs the Pallas grid locally; the per-lane validity mask
gathers back over ICI, and a psum'd failure count gives the block-level
verdict without a host round trip. This is the 8-chip scale-out of the
CCheckQueue replacement: the reference's `-par=N` worker threads become mesh
shards.

On CPU meshes (the virtual-8 dryrun/bench — no Mosaic backend) the same
kernel runs in pallas interpret mode, so the sharded program is the real
w4 pipeline everywhere, not a stand-in ladder (VERDICT r4 #3/weak-3).

The GLV kernel (ops/secp256k1._glv_program, -ecdsakernel=glv, the
default) shards the same way via _sharded_glv_jit — plain XLA end to
end, so no interpret split: the fixed-base comb constants replicate per
chip and the split-scalar byte matrices shard on the batch axis.

Since ISSUE 11 the GLV path shards the FUSED device-decompose program
(ops/secp256k1._glv_dev_program) by default: inputs are the same raw
byte matrices as the w4 pipeline (u1/u2 NOT host-split), and each chip
lattice-decomposes its own shard on device — the mesh-native shape the
multi-chip roadmap item needs, with the host-decompose _sharded_glv_jit
kept as the fallback when the fused leg is latched broken.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.secp256k1 import _w4_bytes_program
from .mesh import CHIP_AXIS, chip_mesh, shard_map_nocheck

# per-chip lane granularity: the w4 bytes program reshapes its local batch
# to (8, T) vregs with T a multiple of 128
_CHIP_BUCKET = 1024

# Sharded-MSM program watch (ISSUE 19). Unlike the legacy sig_shard_*
# registrations (mesh-width x load-dependent bucket — counted, baselined
# in tools/bcplint), the MSM shape set IS bounded: per-chip buckets come
# off ops/ecdsa_batch._MSM_BUCKETS (6 rungs) and virtual meshes sweep
# widths {1, 2, 4, 8}, so the signature space is 6 x 4.
from ..util import devicewatch as _dw

_PW_SHARD_MSM = _dw.program("sig_shard_msm", shape_budget=24)


def _use_interpret(n_chips: int) -> bool:
    """Interpret mode iff the mesh's devices are CPUs — NOT the default
    backend: an accelerator plugin can win default-backend selection while
    the virtual mesh is still CPU (tests/conftest.py documents the same
    trap), and Mosaic-vs-interpret must follow where the kernel RUNS."""
    return chip_mesh(n_chips).devices.flat[0].platform == "cpu"


@partial(jax.jit, static_argnames=("n_chips",))
def _sharded_glv_jit(d1m, d2m, sg1, sg2, s1m, s2m, ydiff8, qxb, qyb,
                     qinf8, r0b, rnb, wrap8, n_chips: int):
    """GLV analogue of _sharded_w4_jit: the plain-XLA GLV program
    (ops/secp256k1._glv_program) sharded on the batch axis — no
    interpret-mode split needed because the GLV core never enters Mosaic
    (its fixed-base comb rides as captured XLA constants, replicated per
    chip by the partitioner)."""
    from ..ops.secp256k1 import _glv_program

    mesh = chip_mesh(n_chips)
    row = P(CHIP_AXIS)

    def body(d1m, d2m, sg1, sg2, s1m, s2m, ydiff8, qxb, qyb, qinf8, r0b,
             rnb, wrap8):
        out = _glv_program(d1m, d2m, sg1, sg2, s1m, s2m, ydiff8, qxb, qyb,
                           qinf8, r0b, rnb, wrap8)
        b_local = qxb.shape[0]
        ok = out[0].reshape(b_local).astype(bool)
        degen = out[1].reshape(b_local).astype(bool)
        fails = jax.lax.psum(
            jnp.sum(((~ok | degen) & (qinf8 == 0)).astype(jnp.uint32)),
            CHIP_AXIS,
        )
        return ok, degen, fails

    fn = shard_map_nocheck(
        body,
        mesh,
        in_specs=(row,) * 13,
        out_specs=(P(CHIP_AXIS), P(CHIP_AXIS), P()),
    )
    return fn(d1m, d2m, sg1, sg2, s1m, s2m, ydiff8, qxb, qyb, qinf8, r0b,
              rnb, wrap8)


@partial(jax.jit, static_argnames=("n_chips",))
def _sharded_glv_dev_jit(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8,
                         n_chips: int):
    """Sharded FUSED decompose+verify GLV program (ISSUE 11): raw scalar
    byte matrices shard on the batch axis and every chip runs the exact
    in-kernel lattice split over its own lanes — the host ships bytes,
    never split scalars. Plain XLA end to end (no interpret split)."""
    from ..ops.secp256k1 import _glv_dev_program

    mesh = chip_mesh(n_chips)
    row = P(CHIP_AXIS)

    def body(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8):
        out = _glv_dev_program(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8)
        b_local = qxb.shape[0]
        ok = out[0].reshape(b_local).astype(bool)
        degen = out[1].reshape(b_local).astype(bool)
        fails = jax.lax.psum(
            jnp.sum(((~ok | degen) & (qinf8 == 0)).astype(jnp.uint32)),
            CHIP_AXIS,
        )
        return ok, degen, fails

    fn = shard_map_nocheck(
        body,
        mesh,
        in_specs=(row,) * 8,
        out_specs=(P(CHIP_AXIS), P(CHIP_AXIS), P()),
    )
    return fn(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8)


@partial(jax.jit, static_argnames=("n_chips", "interpret"))
def _sharded_w4_jit(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8,
                    n_chips: int, interpret: bool):
    mesh = chip_mesh(n_chips)
    row = P(CHIP_AXIS)  # (B, 32) byte matrices: shard the batch axis

    def body(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8):
        out = _w4_bytes_program(u1m, u2m, qxb, qyb, qinf8, r0b, rnb,
                                wrap8, interpret=interpret)
        b_local = u1m.shape[0]
        ok = out[0].reshape(b_local).astype(bool)
        degen = out[1].reshape(b_local).astype(bool)
        # block verdict: total failures among real (non-poisoned) lanes,
        # reduced over ICI (degenerate lanes settle on host; count them
        # as failures here so the fast verdict stays conservative)
        fails = jax.lax.psum(
            jnp.sum(((~ok | degen) & (qinf8 == 0)).astype(jnp.uint32)),
            CHIP_AXIS,
        )
        return ok, degen, fails

    fn = shard_map_nocheck(
        body,
        mesh,
        in_specs=(row,) * 8,
        out_specs=(P(CHIP_AXIS), P(CHIP_AXIS), P()),
        # pallas_call's out_shape carries no varying-mesh-axes annotation;
        # the specs state the sharding explicitly (check disabled)
    )
    return fn(u1m, u2m, qxb, qyb, qinf8, r0b, rnb, wrap8)


@partial(jax.jit, static_argnames=("n_chips",))
def _sharded_msm_jit(xm, ym, inf8, km, n_chips: int):
    """Sharded Pippenger MSM (ISSUE 19): the TERM axis shards across the
    mesh — MSM is a sum, so it distributes over row shards with no
    cross-chip traffic during accumulation. Each chip runs the full
    bucket-accumulation pipeline (ops/secp256k1._msm_accumulate) over its
    local terms and emits its packed (61, 1) Jacobian partial; the host
    folds n_chips partials with the Python-int oracle (a length-n_chips
    fold of exact point adds — microseconds, and it keeps the
    accept-side completeness argument in one place instead of re-proving
    it for a psum tree of in-field adds)."""
    from ..ops.secp256k1 import _msm_accumulate

    mesh = chip_mesh(n_chips)
    row = P(CHIP_AXIS)

    def body(xm, ym, inf8, km):
        acc = _msm_accumulate(xm, ym, inf8, km)
        return jnp.concatenate(
            [acc["X"], acc["Y"], acc["Z"],
             acc["inf"].astype(jnp.uint32).reshape(1, 1)], axis=0)

    fn = shard_map_nocheck(
        body,
        mesh,
        in_specs=(row, row, row, row),
        out_specs=P(None, CHIP_AXIS),  # (61, n_chips) packed partials
    )
    return fn(xm, ym, inf8, km)


def msm_is_infinity_sharded(terms, n_chips: int) -> bool:
    """Batch-equation check over the mesh: ``terms`` is the host-side
    [(x, y, scalar)] list from the Schnorr batch equation
    (ops/ecdsa_batch builds it); returns True iff Σ kᵢ·Pᵢ is the point
    at infinity. Pads the term count to an MSM bucket per chip so the
    compiled shapes stay on the declared ladder."""
    from ..crypto import secp256k1 as oracle
    from ..ops.ecdsa_batch import _msm_bucket_for, _msm_pack
    from ..ops.secp256k1 import N_LIMBS, from_limbs_np
    from ..util import devicewatch as dw

    per_chip = _msm_bucket_for(
        max(1, (len(terms) + n_chips - 1) // n_chips))
    bucket = per_chip * n_chips
    arrays = [np.asarray(a) for a in _msm_pack(terms, bucket)]
    dw.note_transfer("sig_shard", "h2d",
                     sum(int(a.nbytes) for a in arrays))
    with _PW_SHARD_MSM.dispatch((bucket, n_chips)):
        out = np.asarray(jax.block_until_ready(
            _sharded_msm_jit(*arrays, n_chips=n_chips)))
    # host fold: Jacobian partials -> affine -> oracle point_add chain
    acc = None
    for c in range(n_chips):
        col = out[:, c]
        if col[3 * N_LIMBS]:
            continue  # chip saw only padded lanes
        x = from_limbs_np(col[0:N_LIMBS]) % oracle.P
        y = from_limbs_np(col[N_LIMBS:2 * N_LIMBS]) % oracle.P
        z = from_limbs_np(col[2 * N_LIMBS:3 * N_LIMBS]) % oracle.P
        if z == 0:
            continue
        zi = pow(z, oracle.P - 2, oracle.P)
        pt = ((x * zi * zi) % oracle.P,
              (y * zi * zi * zi) % oracle.P)
        acc = pt if acc is None else oracle.point_add(acc, pt)
    return acc is None


def verify_batch_sharded(records, n_chips: int,
                         kernel: str | None = None) -> np.ndarray:
    """Shard a record batch across the mesh; returns (len(records),) bool.
    Pads B up to n_chips * 1024-lane shards with poisoned lanes; degenerate
    lanes (H == 0 collisions) re-verify on the host scalar path exactly
    like the single-chip dispatch (ops/ecdsa_batch.BatchHandle). ``kernel``
    overrides the -ecdsakernel selection for this call (None = active)."""
    from ..ops import ecdsa_batch
    from ..ops.ecdsa_batch import (
        _verify_cpu,
        pack_records_glv,
        pack_records_w4_bytes,
    )

    n = len(records)
    per_chip = max(
        _CHIP_BUCKET,
        ((n + n_chips - 1) // n_chips + _CHIP_BUCKET - 1)
        // _CHIP_BUCKET * _CHIP_BUCKET,
    )
    bucket = per_chip * n_chips
    from ..util import devicewatch as dw

    kern = kernel if kernel in ecdsa_batch.ECDSA_KERNELS \
        else ecdsa_batch.active_kernel()
    if (kern == "glv" and ecdsa_batch.glv_enabled()
            and ecdsa_batch.glv_dev_enabled()):
        # fused device-decompose program: the host pack is the w4 byte
        # emit, each chip splits its own scalar shard in-kernel
        arrays = [np.asarray(a)
                  for a in pack_records_w4_bytes(records, bucket)]
        dw.note_transfer("sig_shard", "h2d",
                         sum(int(a.nbytes) for a in arrays))
        # mesh-width x bucket is the compiled-shape signature; no budget —
        # virtual meshes legitimately sweep 1/2/4/8
        with dw.program("sig_shard_glv_dev").dispatch((bucket, n_chips)):
            ok, degen, _fails = jax.block_until_ready(
                _sharded_glv_dev_jit(*arrays, n_chips=n_chips)
            )
    elif kern == "glv" and ecdsa_batch.glv_enabled():
        arrays = [np.asarray(a) for a in pack_records_glv(records, bucket)]
        dw.note_transfer("sig_shard", "h2d",
                         sum(int(a.nbytes) for a in arrays))
        with dw.program("sig_shard_glv").dispatch((bucket, n_chips)):
            ok, degen, _fails = jax.block_until_ready(
                _sharded_glv_jit(*arrays, n_chips=n_chips)
            )
    else:
        arrays = [np.asarray(a)
                  for a in pack_records_w4_bytes(records, bucket)]
        dw.note_transfer("sig_shard", "h2d",
                         sum(int(a.nbytes) for a in arrays))
        with dw.program("sig_shard_w4").dispatch((bucket, n_chips)):
            ok, degen, _fails = jax.block_until_ready(
                _sharded_w4_jit(*arrays, n_chips=n_chips,
                                interpret=_use_interpret(n_chips))
            )
    out = np.asarray(ok)[:n].copy()
    degen = np.asarray(degen)[:n]
    idxs = np.nonzero(degen)[0]
    if idxs.size:
        from ..ops.ecdsa_batch import STATS

        STATS.degenerate_rechecks += int(idxs.size)
        out[idxs] = _verify_cpu([records[i] for i in idxs])
    return out


def dryrun(n_devices: int) -> None:
    """Driver dryrun leg: one sharded w4 sig-batch dispatch on the virtual
    mesh — one valid and one invalid signature among padded lanes."""
    import random

    from ..crypto import secp256k1 as oracle
    from ..script.interpreter import SigCheckRecord

    rng = random.Random(1)
    recs, expected = [], []
    for i in range(2):
        d = rng.randrange(1, oracle.N)
        pub = oracle.point_mul(d, oracle.G)
        e = rng.randrange(1 << 256)
        r, s = oracle.ecdsa_sign(d, e)
        if i == 1:
            e ^= 1  # corrupt: lane must report False
        recs.append(SigCheckRecord(pub, r, s, e))
        expected.append(oracle.ecdsa_verify(pub, r, s, e))
    from ..ops.ecdsa_batch import active_kernel

    got = verify_batch_sharded(recs, n_devices)
    assert got.tolist() == expected, (got.tolist(), expected)
    print(f"sig_shard dryrun: {n_devices}-chip sharded "
          f"{active_kernel()} sig batch OK")
