"""Multi-chip ECDSA batch sharding (P1 in SURVEY.md §3.2).

The signature-batch axis is embarrassingly parallel: shard the B lanes of
ops/secp256k1.ecdsa_verify_batch_device across the ('chip',) mesh with
shard_map — each chip verifies B/n_chips lanes, the per-lane validity mask
gathers back over ICI (out_spec P('chip')), and a psum'd failure count
gives the block-level verdict without materializing the mask on host
first. This is the 8-chip scale-out of the CCheckQueue replacement: the
reference's `-par=N` worker threads become mesh shards.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..ops.secp256k1 import ecdsa_verify_batch_device
from .mesh import CHIP_AXIS, chip_mesh


@partial(jax.jit, static_argnames=("n_chips",))
def _sharded_verify_jit(u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok,
                        n_chips: int):
    mesh = chip_mesh(n_chips)
    lane = P(None, CHIP_AXIS)  # (256,B) / (20,B): shard the batch axis

    def body(u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok):
        ok = ecdsa_verify_batch_device(
            u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok
        )
        # block verdict: total failures among real (non-poisoned... the
        # caller masks padding) lanes, reduced over ICI
        fails = jax.lax.psum(
            jnp.sum((~ok & ~q_inf).astype(jnp.uint32)), CHIP_AXIS
        )
        return ok, fails

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(lane, lane, lane, lane, P(CHIP_AXIS), lane, lane,
                  P(CHIP_AXIS)),
        out_specs=(P(CHIP_AXIS), P()),
    )
    return fn(u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok)


def verify_batch_sharded(records, n_chips: int) -> np.ndarray:
    """Shard a record batch across the mesh; returns (len(records),) bool.
    Pads B to a multiple of n_chips with poisoned lanes."""
    from ..ops.ecdsa_batch import pack_records

    n = len(records)
    bucket = max(n_chips, ((n + n_chips - 1) // n_chips) * n_chips)
    arrays = pack_records(records, bucket)
    ok, _fails = jax.block_until_ready(
        _sharded_verify_jit(*map(np.asarray, arrays), n_chips=n_chips)
    )
    return np.asarray(ok)[:n]


def dryrun(n_devices: int) -> None:
    """Driver dryrun leg: one sharded sig-batch dispatch on the virtual
    mesh — one valid and one invalid signature among padded lanes."""
    import random

    from ..crypto import secp256k1 as oracle
    from ..script.interpreter import SigCheckRecord

    rng = random.Random(1)
    recs, expected = [], []
    for i in range(2):
        d = rng.randrange(1, oracle.N)
        pub = oracle.point_mul(d, oracle.G)
        e = rng.randrange(1 << 256)
        r, s = oracle.ecdsa_sign(d, e)
        if i == 1:
            e ^= 1  # corrupt: lane must report False
        recs.append(SigCheckRecord(pub, r, s, e))
        expected.append(oracle.ecdsa_verify(pub, r, s, e))
    got = verify_batch_sharded(recs, n_devices)
    assert got.tolist() == expected, (got.tolist(), expected)
    print(f"sig_shard dryrun: {n_devices}-chip sharded sig batch OK")
