"""Multi-chip parallelism over a jax.sharding.Mesh.

The reference's only intra-node parallelism is shared-memory threads
(src/checkqueue.h CCheckQueue; SURVEY.md §3.2). Here the equivalents are
SPMD over a ('chip',) mesh with XLA collectives riding ICI:

  - nonce_shard.py — the 32-bit PoW nonce space sharded across chips
    (P2 in SURVEY.md §3.2): each chip sweeps a contiguous range, hit
    reduction via psum/argmin of (found, nonce).
  - The ECDSA batch axis (P1) shards the same way in ops/ecdsa_batch.py.

Tests exercise these on a virtual 8-device CPU mesh
(xla_force_host_platform_device_count); the driver's dryrun_multichip does
the same, and real runs use the v5e-8 ICI ring.
"""

from .mesh import chip_mesh, device_count

__all__ = ["chip_mesh", "device_count"]
