"""Multi-chip PoW nonce-space sharding (P2 in SURVEY.md §3.2).

The reference mines one nonce at a time on one CPU thread
(src/rpc/mining.cpp:~120 generateBlocks); real deployments shard the nonce +
extranonce space across machines via getblocktemplate. Here the 32-bit nonce
space is sharded across TPU chips directly: `shard_map` over a ('chip',)
mesh, each chip sweeping a contiguous stripe with the single-chip tile loop
(ops/miner.sweep_jit's body), and the winning (found, nonce) reduced over ICI
with a min-nonce `psum`-style reduction — the payload is 2 scalars, so the
collective cost is negligible next to the hash work.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..crypto.hashes import header_midstate
from ..ops.miner import DEFAULT_TILE, _sweep_tile
from ..ops.sha256 import bytes_to_words_np, target_to_limbs_np
from ..ops.sha256_sweep import hoist_template
from .mesh import CHIP_AXIS, chip_mesh, local_devices, shard_map_nocheck


def _shard_body(midstate, tail, target_limbs, start_nonce, n_tiles, tile: int):
    """Per-chip sweep of a contiguous stripe of the nonce space.

    Runs under shard_map: axis_index picks this chip's stripe. Returns
    (found, nonce) reduced across chips to the globally smallest hit nonce
    (deterministic winner regardless of which chip finds one first).
    """
    chip = jax.lax.axis_index(CHIP_AXIS).astype(jnp.uint32)
    if hasattr(jax.lax, "axis_size"):
        n_chips = jnp.uint32(jax.lax.axis_size(CHIP_AXIS))
    else:  # pre-0.6 jax: count the axis with an all-ones psum
        n_chips = jax.lax.psum(jnp.uint32(1), CHIP_AXIS)
    stripe = start_nonce + chip * n_tiles * np.uint32(tile)

    tgt = [target_limbs[j] for j in range(8)]
    # per-template chunk-2 hoist, once per dispatch (shared across every
    # tile of this chip's stripe — the same pre the single-chip sweep uses)
    pre = hoist_template([midstate[i] for i in range(8)],
                         [tail[i] for i in range(3)])

    def cond(carry):
        i, found, _ = carry
        return jnp.logical_and(i < n_tiles, jnp.logical_not(found))

    def body(carry):
        i, _, _ = carry
        base = stripe + i * np.uint32(tile)
        hit, nonce = _sweep_tile(pre, tgt, base, tile)
        return i + jnp.uint32(1), hit, nonce

    # Initial carry must be device-varying (derived from `stripe`, which
    # carries the chip axis) — shard_map rejects an invariant init whose
    # body output varies per chip.
    zero_v = stripe * jnp.uint32(0)
    tiles, found, nonce = jax.lax.while_loop(
        cond, body, (zero_v, zero_v > jnp.uint32(0), zero_v)
    )
    # Reduce to the smallest found nonce across chips; losers contribute MAX.
    key = jnp.where(found, nonce, jnp.uint32(0xFFFFFFFF))
    # Tie-break toward lower nonce; a lone 0xFFFFFFFF hit is recovered via
    # any_found (it would be indistinguishable from "none" by key alone).
    best = jax.lax.pmin(key, CHIP_AXIS)
    any_found = jax.lax.pmax(found.astype(jnp.uint32), CHIP_AXIS) > 0
    total_tiles = jax.lax.psum(tiles, CHIP_AXIS)
    # per-chip tiles-done, gathered over the chip axis (shard imbalance
    # observability — SURVEY §6.5; bench config 5 reports the vector)
    per_chip = tiles.reshape(1)
    return any_found, best, total_tiles, per_chip


@partial(jax.jit, static_argnames=("tile", "n_chips"))
def _sharded_sweep_jit(midstate, tail, target_limbs, start_nonce, n_tiles,
                       tile: int, n_chips: int):
    mesh = chip_mesh(n_chips)
    fn = shard_map_nocheck(
        partial(_shard_body, tile=tile),
        mesh,
        in_specs=(P(), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(CHIP_AXIS)),
    )
    return fn(midstate, tail, target_limbs, start_nonce, n_tiles)


def sweep_header_sharded(header80: bytes, target: int, start_nonce: int = 0,
                         max_nonces: int = 1 << 32,
                         tile: int = DEFAULT_TILE,
                         n_chips: int | None = None,
                         return_per_chip: bool = False):
    """Host API: multi-chip PoW search. Returns (nonce or None, total_hashes)
    — or (nonce, total_hashes, per_chip_tiles) with return_per_chip.

    Same signature contract as ops.miner.sweep_header so callers
    (mining/generate.mine_block's `sweep` hook) can inject either. max_nonces
    is the TOTAL budget across chips; chip c owns the contiguous stripe
    [start + c*span, start + (c+1)*span) with span = max_nonces / n_chips.
    """
    assert len(header80) == 80
    if n_chips is None:
        n_chips = len(local_devices())
    midstate = jnp.asarray(np.array(header_midstate(header80), dtype=np.uint32))
    tail = jnp.asarray(
        bytes_to_words_np(np.frombuffer(header80[64:76], dtype=np.uint8))
    )
    tgt = jnp.asarray(target_to_limbs_np(target))
    n_tiles = max(1, max_nonces // n_chips // tile)
    found, nonce, tiles, per_chip = _sharded_sweep_jit(
        midstate, tail, tgt, jnp.uint32(start_nonce), jnp.uint32(n_tiles),
        tile=tile, n_chips=n_chips,
    )
    hashes = int(tiles) * tile
    result = int(nonce) if bool(found) else None
    if return_per_chip:
        return result, hashes, [int(v) for v in np.asarray(per_chip)]
    return result, hashes
