"""Device mesh construction.

One 1-D mesh axis ('chip',) spanning all local devices — the v5e-8 target is
a single host with 8 chips in a 2x4 ICI ring (SURVEY.md §6.8); a 1-D logical
axis is the right shape because both sharded workloads (nonce sweep, sig
batch) are embarrassingly parallel with a single tiny reduction.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

CHIP_AXIS = "chip"


def local_devices(min_count: int = 1) -> list:
    """Devices for the mesh. Honors JAX_PLATFORMS explicitly because the
    axon TPU plugin registers itself as the default backend regardless of
    that env var — tests/dryrun set JAX_PLATFORMS=cpu +
    xla_force_host_platform_device_count=N and must get the N virtual CPU
    devices, not the tunneled TPU. Falls back to the CPU backend when the
    default backend is too small (driver dryrun_multichip path)."""
    plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
    if plat:
        try:
            return jax.devices(plat)
        except RuntimeError:
            pass
    devs = jax.devices()
    if len(devs) < min_count:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= min_count:
                return cpu
        except RuntimeError:
            pass
    return devs


def device_count() -> int:
    return len(local_devices())


def chip_mesh(n: int | None = None) -> Mesh:
    """Mesh over the first n local devices (default: all)."""
    devs = local_devices(min_count=n or 1)
    if n is not None:
        if n > len(devs):
            raise ValueError(f"requested {n} devices, have {len(devs)}")
        devs = devs[:n]
    return Mesh(np.array(devs), (CHIP_AXIS,))


def shard_map_nocheck(body, mesh: Mesh, in_specs, out_specs):
    """shard_map with the replication/VMA check disabled, portable across
    jax versions: the kwarg is ``check_vma`` on current jax and
    ``check_rep`` before the rename — and the check must be off either
    way (pallas_call's out_shape carries no varying-mesh-axes annotation,
    and older jax has no replication rule for while_loop at all)."""
    import inspect

    try:
        from jax import shard_map as _sm
    except ImportError:  # pre-0.6 jax ships it under experimental only
        from jax.experimental.shard_map import shard_map as _sm
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    params = inspect.signature(_sm).parameters
    kwargs["check_vma" if "check_vma" in params else "check_rep"] = False
    return _sm(body, **kwargs)
