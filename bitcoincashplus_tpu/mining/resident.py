"""Device-resident mining loop (the ISSUE 10 tentpole).

BENCH_r05 measured the nonce sweep at 0.59 MH/s device-resident but
0.04 MH/s end-to-end — ~15x lost to host dispatch — and BENCH_r08's
per-phase decomposition pinned the blame on per-call enqueue/fetch, not
the kernel (ROOFLINE.md has the kernel at 88% of its op-bound ceiling).
The per-call shape (``ops/miner.sweep_header``) pays, on EVERY poll:
host->device staging of the template (midstate/tail/target), a fresh
program dispatch, a blocking scalar fetch, and the full devicewatch/
breaker bookkeeping — serially, with the device idle between calls.

``ResidentSweep`` keeps the sweep resident instead:

- **One compiled program, long-lived buffers.** The template (midstate,
  tail words, target limbs) lives in device buffers; a template refresh
  is a same-shape buffer swap (``set_template``), never a retrace — the
  compiled shape is keyed only by the static tile, declared to the
  devicewatch compile sentinel as the ``miner_resident`` program with a
  shape budget. The retrace-sentinel test asserts repeated swaps stay
  inside it.
- **Pipelined segments.** The nonce space is swept in fixed-size
  segments (``seg_tiles`` tiles per dispatch); up to ``inflight``
  segments ride the device queue at once (JAX async dispatch), so the
  host settles segment k while k+1 already executes — enqueue/fetch
  overhead overlaps the hash work instead of serializing with it.
- **On-chip nonce-space rollover.** Segment arithmetic is uint32; the
  host cursor clamps each segment at the 2^32 boundary
  (``ops/miner._boundary_tiles`` semantics) and wraps to 0, counting
  passes — a sweep crossing the boundary continues at nonce 0 without
  re-hashing the straddled range and without a fresh program.
- **Candidate-hit FIFO.** Device hits are host exact-verified (the
  scalar oracle — 2 hashes, free next to a sweep) and pushed into a
  bounded FIFO the caller polls; with the truncated-h7 kernel a false
  positive (limb7 tie, ~2^-32) is resumed past synchronously, so
  results stay bit-identical to the CPU oracle.

``sweep()`` adapts the loop to the ``sweep_header`` contract (first hit
in nonce order wins, ``(nonce | None, hashes_attempted)``) so
``mining/generate.mine_block`` and ``node._select_sweep`` drive the
persistent loop through the supervised-dispatch/breaker path unchanged:
a dead device degrades to the scalar host loop under the miner breaker,
and every settle beats the ``miner`` watchdog subsystem.

Telemetry: ``bcp_mining_*`` counter/histogram families below (native,
TYPEs per the PR 6/PR 7 lessons); the node projects ``snapshot()`` into
``bcp_mining_state_*`` gauges and ``gettpuinfo.mining``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np

from ..crypto.hashes import header_midstate, sha256d
from ..util import devicewatch as dw
from ..util import telemetry as tm

PROGRAM = "miner_resident"
# compiled-shape budget for the resident program: (kernel, tile)
# specializations — a node mints at most the exact + h7 kernels at the
# production tile plus a regtest/bench tile each; a template swap that
# starts recompiling trips the sentinel (asserted in the mining tests)
SHAPE_BUDGET = 4

_TILES_C = tm.counter(
    "bcp_mining_tiles_swept_total",
    "Nonce tiles swept by the resident mining loop")
_CANDS_C = tm.counter(
    "bcp_mining_candidates_total",
    "Device candidate hits by outcome (confirmed = host-verified PoW hit, "
    "false_positive = truncated-limb tie resumed past, stale = hit from a "
    "pre-swap template generation, dropped = FIFO overflow)",
    labels=("result",))
_SWAPS_C = tm.counter(
    "bcp_mining_template_swaps_total",
    "Template refreshes applied as device buffer swaps (no retrace)")
_POLLS_C = tm.counter(
    "bcp_mining_polls_total",
    "Host polls of the resident loop (one settled segment each)")
_ROLLOVER_C = tm.counter(
    "bcp_mining_rollovers_total",
    "Nonce-space rollovers (cursor wrapped past 2^32 to 0)")
_POLL_H = tm.histogram(
    "bcp_mining_poll_seconds",
    "Blocking settle wait per resident-loop poll (the d2h scalar fetch "
    "of the oldest in-flight segment)")
_FIFO_G = tm.gauge(
    "bcp_mining_fifo_depth",
    "Confirmed candidate hits parked in the resident loop's FIFO")


def _clamp_segment(cursor: int, want: int, tile: int, cap: int):
    """Boundary-clamped ``(n_tiles, nonces)`` for a segment at ``cursor``:
    the shared ops/miner._boundary_tiles clamp (no wrap past 2^32 inside
    one dispatch) plus the per-segment tile cap."""
    from ..ops.miner import _boundary_tiles

    n_tiles = min(cap, _boundary_tiles(cursor, want, tile))
    return n_tiles, min(n_tiles * tile, (1 << 32) - cursor)


class _Segment:
    __slots__ = ("gen", "start", "n_tiles", "nonces", "out")

    def __init__(self, gen, start, n_tiles, nonces, out):
        self.gen = gen              # template generation at enqueue
        self.start = start          # first nonce of the segment
        self.n_tiles = n_tiles
        self.nonces = nonces        # boundary-clamped nonce count
        self.out = out              # (found, nonce, tiles) device futures


class ResidentSweep:
    """Long-lived device-resident PoW sweep (see module docstring).

    ``kernel``: "exact" runs the full 8-limb on-device compare
    (ops/miner.sweep_jit — no false positives); "h7" runs the truncated
    top-limb kernel (ops/sha256_sweep.sweep_fast_jit — fewer ops/nonce,
    candidates host-verified). ``tile`` is the STATIC compiled shape;
    the loop never recompiles for a template swap, only for a new
    (kernel, tile) pair, bounded by the devicewatch shape budget."""

    def __init__(self, tile: int = 1 << 16, seg_tiles: int = 8,
                 inflight: int = 2, fifo_depth: int = 16,
                 kernel: str = "exact"):
        if kernel not in ("exact", "h7"):
            raise ValueError(f"resident kernel {kernel!r}: exact or h7")
        self.tile = int(tile)
        self.seg_tiles = max(1, int(seg_tiles))
        self.inflight = max(1, int(inflight))
        self.kernel = kernel
        self.fifo = deque(maxlen=max(1, int(fifo_depth)))
        self.generation = 0
        self._header76: Optional[bytes] = None
        self._target: Optional[int] = None
        self._mid = self._tail = self._tgt = None   # device buffers
        self._mid_np = self._tail_np = self._tgt_np = None
        self._cursor = 0
        self._segments: deque[_Segment] = deque()
        self._watchdog = False
        # cumulative stats (snapshot() / gettpuinfo.mining)
        self.tiles_swept = 0
        self.nonces_swept = 0
        self.passes = 0
        self.buffer_swaps = 0
        self.polls = 0
        self.hits = 0
        self.false_positives = 0
        self.stale_hits = 0
        self.segments_discarded = 0
        self.fifo_dropped = 0
        self._poll_ema_s = 0.0      # inter-poll cadence (EMA)
        self._last_poll_t = 0.0

    # -- template lifecycle (buffer swap, never a retrace) --------------

    def set_template(self, header80: bytes, target: int) -> int:
        """Install a template. A changed (header bytes 0..75, target)
        swaps the device buffers in place — same shapes, same compiled
        program — bumps the generation, and invalidates in-flight
        segments (their results are counted stale, never trusted).
        Idempotent for an unchanged template."""
        import jax.numpy as jnp

        from ..ops.sha256 import bytes_to_words_np, target_to_limbs_np

        assert len(header80) == 80
        header76 = header80[:76]
        if header76 == self._header76 and target == self._target:
            return self.generation
        self._header76 = header76
        self._target = target
        self._mid_np = np.array(header_midstate(header80), dtype=np.uint32)
        self._tail_np = bytes_to_words_np(
            np.frombuffer(header80[64:76], dtype=np.uint8))
        limbs = target_to_limbs_np(target)
        self._tgt_np = (np.uint32(limbs[7]) if self.kernel == "h7"
                        else limbs)
        nbytes = int(self._mid_np.nbytes + self._tail_np.nbytes
                     + np.asarray(self._tgt_np).nbytes)
        dw.note_transfer("miner_resident", "h2d", nbytes)
        # the swap: fresh same-shape device buffers replace the old ones
        # (the old buffers are freed once their in-flight segments settle)
        self._mid = jnp.asarray(self._mid_np)
        self._tail = jnp.asarray(self._tail_np)
        self._tgt = jnp.asarray(self._tgt_np)
        self.generation += 1
        self.buffer_swaps += 1
        _SWAPS_C.inc()
        self._cursor = 0
        return self.generation

    # -- segment pipeline -----------------------------------------------

    def _jitfn(self):
        if self.kernel == "h7":
            from ..ops.sha256_sweep import sweep_fast_jit

            return sweep_fast_jit
        from ..ops.miner import sweep_jit

        return sweep_jit

    def _dispatch(self, start: int, n_tiles: int):
        """Enqueue one segment dispatch under the compile sentinel; the
        shape signature is (kernel, tile) — template swaps re-dispatch
        the SAME signature, so the shapes count must stay flat."""
        import jax.numpy as jnp

        jitfn = self._jitfn()
        args = (self._mid_np, self._tail_np, self._tgt_np,
                np.uint32(start), np.uint32(n_tiles))
        with dw.program(PROGRAM, shape_budget=SHAPE_BUDGET).dispatch(
                self.kernel, self.tile, jitfn=jitfn, args=args,
                kwargs={"tile": self.tile}):
            out = jitfn(self._mid, self._tail, self._tgt,
                        jnp.uint32(start), jnp.uint32(n_tiles),
                        tile=self.tile)
        dw.note_transfer("miner_resident", "h2d", 8)  # 2 uint32 scalars
        return out

    def _pump(self, budget_left: int) -> int:
        """Enqueue segments (rollover-aware) until the in-flight window
        is full or ``budget_left`` nonces are covered; returns the nonce
        count newly planned."""
        planned = 0
        while (len(self._segments) < self.inflight
               and budget_left - planned > 0):
            n_tiles, nonces = _clamp_segment(
                self._cursor, budget_left - planned, self.tile,
                self.seg_tiles)
            out = self._dispatch(self._cursor, n_tiles)
            self._segments.append(_Segment(
                self.generation, self._cursor, n_tiles, nonces, out))
            planned += nonces
            self._cursor = (self._cursor + nonces) & 0xFFFFFFFF
            if self._cursor == 0:
                self.passes += 1
                _ROLLOVER_C.inc()
        return planned

    def _settle_oldest(self):
        """Block on the oldest in-flight segment; returns (seg, found,
        cand_nonce, tiles_done). Meters the poll, beats the watchdog."""
        seg = self._segments.popleft()
        t0 = time.perf_counter()
        found, nonce, tiles = seg.out
        found = bool(found)
        nonce = int(nonce)
        tiles = int(tiles)
        dt = time.perf_counter() - t0
        _POLL_H.observe(dt)
        _POLLS_C.inc()
        dw.note_transfer("miner_resident", "d2h", 12, seconds=dt)
        dw.note_phase("miner_resident", "fetch", dt)
        now = time.perf_counter()
        if self._last_poll_t:
            gap = now - self._last_poll_t
            self._poll_ema_s = (gap if self._poll_ema_s == 0.0
                                else 0.8 * self._poll_ema_s + 0.2 * gap)
        self._last_poll_t = now
        self.polls += 1
        done_tiles = tiles
        self.tiles_swept += done_tiles
        _TILES_C.inc(done_tiles)
        dw.WATCHDOG.beat("miner")
        return seg, found, nonce, tiles

    def _confirm(self, nonce: int) -> bool:
        """Host exact-verify of a device candidate (the scalar oracle)."""
        hdr = self._header76 + int(nonce).to_bytes(4, "little")
        return int.from_bytes(sha256d(hdr), "little") <= self._target

    def _resweep_exact(self, start: int, nonces_left: int):
        """Synchronous in-segment resume past an h7 false positive
        (~2^-32 per hash): sweep [start, start+nonces_left) blocking.
        Returns ``(hit, hashed)`` — the first CONFIRMED hit (or None) and
        the number of nonces hashed here, which the caller must fold into
        its attempted-hash accounting (the per-dispatch twin
        sweep_header_fast counts resumed work the same way)."""
        hashed = 0
        while nonces_left > 0:
            n_tiles, nonces = _clamp_segment(
                start, nonces_left, self.tile, self.seg_tiles)
            out = self._dispatch(start, n_tiles)
            found, cand, tiles = bool(out[0]), int(out[1]), int(out[2])
            done = min(tiles * self.tile, nonces)
            self.tiles_swept += tiles
            self.nonces_swept += done
            hashed += done
            _TILES_C.inc(tiles)
            if not found:
                return None, hashed
            if self._confirm(cand):
                return cand, hashed
            self.false_positives += 1
            _CANDS_C.labels(result="false_positive").inc()
            consumed = ((cand - start) & 0xFFFFFFFF) + 1
            nonces_left -= consumed
            start = (cand + 1) & 0xFFFFFFFF
        return None, hashed

    # -- the sweep_header-contract driver -------------------------------

    def sweep(self, header80: bytes, target: int, start_nonce: int = 0,
              max_nonces: int = 1 << 32, tile: Optional[int] = None):
        """Search [start_nonce, start_nonce+max_nonces) (rollover past
        2^32, one full pass max) for the first nonce in sweep order with
        sha256d(header) <= target. Same contract as
        ops/miner.sweep_header; ``tile`` is accepted for signature
        compatibility and ignored — the resident loop owns its compiled
        tile. A changed header/target is a buffer swap; in-flight
        segments of the old generation are discarded unsettled."""
        gen = self.set_template(header80, target)
        # stale in-flight segments (previous template or previous call's
        # cursor) never contribute: drop the references — the device work
        # completes harmlessly and the buffers are collected
        self.segments_discarded += len(self._segments)
        self._segments.clear()
        self._cursor = start_nonce & 0xFFFFFFFF
        budget = min(max_nonces, 1 << 32)
        swept = 0
        planned = self._pump(budget)
        while self._segments:
            seg, found, cand, tiles = self._settle_oldest()
            done = min(tiles * self.tile, seg.nonces)
            swept += done
            self.nonces_swept += done
            if found and seg.gen != gen:  # defensive: direct-pump users
                self.stale_hits += 1
                _CANDS_C.labels(result="stale").inc()
            elif found:
                if self._confirm(cand):
                    self._record_hit()
                    self.segments_discarded += len(self._segments)
                    self._segments.clear()
                    return cand, swept
                # h7 limb tie: resume synchronously inside the segment
                self.false_positives += 1
                _CANDS_C.labels(result="false_positive").inc()
                after = ((cand - seg.start) & 0xFFFFFFFF) + 1
                hit, hashed = self._resweep_exact(
                    (cand + 1) & 0xFFFFFFFF, seg.nonces - after)
                swept += hashed
                if hit is not None:
                    self._record_hit()
                    self.segments_discarded += len(self._segments)
                    self._segments.clear()
                    return hit, swept
            planned += self._pump(budget - planned)
        return None, swept

    def advance(self, nonce_budget: int) -> int:
        """Continuous-mining poll surface: sweep up to ``nonce_budget``
        nonces forward from the loop's cursor (rollover-aware, template
        already installed via set_template), parking confirmed hits in
        the FIFO for ``take_hits()`` instead of returning the first one —
        the host polls a buffer, it never blocks on (found, nonce,
        tiles). A hit does not stop the sweep; the loop moves on to the
        next segment (at real difficulty a template yields ~one hit, and
        the driver refreshes the template on pickup, so the skipped
        segment remainder is dead work either way). Returns the number
        of new confirmed hits parked."""
        assert self._header76 is not None, "set_template first"
        gen = self.generation
        new_hits = 0
        planned = self._pump(nonce_budget)
        while self._segments:
            seg, found, cand, tiles = self._settle_oldest()
            self.nonces_swept += min(tiles * self.tile, seg.nonces)
            if found and seg.gen == gen and self._confirm(cand):
                self._push_hit(cand)
                new_hits += 1
            elif found and seg.gen == gen:
                # h7 limb tie: the kernel early-exited the segment at the
                # false positive, but the cursor already moved past the
                # whole segment at dispatch time — resume the remainder
                # synchronously (as sweep() does) or a REAL hit in
                # (cand, seg end) would be silently lost until a full
                # 2^32 rollover
                self.false_positives += 1
                _CANDS_C.labels(result="false_positive").inc()
                after = ((cand - seg.start) & 0xFFFFFFFF) + 1
                hit, _ = self._resweep_exact(
                    (cand + 1) & 0xFFFFFFFF, seg.nonces - after)
                if hit is not None:
                    self._push_hit(hit)
                    new_hits += 1
            elif found:
                self.stale_hits += 1
                _CANDS_C.labels(result="stale").inc()
            planned += self._pump(nonce_budget - planned)
        return new_hits

    def _record_hit(self) -> None:
        self.hits += 1
        _CANDS_C.labels(result="confirmed").inc()

    def _push_hit(self, nonce: int) -> None:
        """Park a confirmed hit in the bounded FIFO (oldest dropped on
        overflow, metered — the host poll cadence bounds staleness)."""
        if len(self.fifo) == self.fifo.maxlen:
            self.fifo_dropped += 1
            _CANDS_C.labels(result="dropped").inc()
        self.fifo.append({"nonce": int(nonce),
                          "generation": self.generation})
        self._record_hit()
        _FIFO_G.set(len(self.fifo))

    def take_hits(self) -> list:
        """Drain the confirmed-candidate FIFO (host poll surface)."""
        out = list(self.fifo)
        self.fifo.clear()
        _FIFO_G.set(0)
        return out

    # -- lifecycle / observability --------------------------------------

    def register_watchdog(self, quiet_s: Optional[float] = None) -> None:
        """Register the ``miner`` stall-watchdog subsystem: pending work
        is the in-flight segment count; every settled poll beats."""
        dw.WATCHDOG.register("miner",
                             pending_fn=lambda: len(self._segments),
                             quiet_s=quiet_s)
        self._watchdog = True

    def close(self) -> None:
        self._segments.clear()
        self._mid = self._tail = self._tgt = None
        if self._watchdog:
            dw.WATCHDOG.unregister("miner")
            self._watchdog = False

    def snapshot(self) -> dict:
        """gettpuinfo's ``mining`` section (resident-loop state)."""
        return {
            "resident": True,
            "kernel": self.kernel,
            "tile": self.tile,
            "seg_tiles": self.seg_tiles,
            "inflight_limit": self.inflight,
            "inflight": len(self._segments),
            "template_generation": self.generation,
            "buffer_swaps": self.buffer_swaps,
            "tiles_swept": self.tiles_swept,
            "nonces_swept": self.nonces_swept,
            "rollover_passes": self.passes,
            "polls": self.polls,
            "poll_cadence_s": round(self._poll_ema_s, 6),
            "fifo_depth": len(self.fifo),
            "fifo_capacity": self.fifo.maxlen,
            "fifo_dropped": self.fifo_dropped,
            "hits": self.hits,
            "false_positives": self.false_positives,
            "stale_hits": self.stale_hits,
            "segments_discarded": self.segments_discarded,
        }
