"""Block template assembly.

Reference: src/miner.cpp:~130 (BlockAssembler::CreateNewBlock), :~440
(IncrementExtraNonce). Package selection over the mempool's ancestor-feerate
index (addPackageTxs :~300) plugs in via the `mempool` argument — with no
mempool the template is coinbase-only (enough for regtest generatetoaddress,
the reference behaves identically on an empty mempool).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field, replace
from typing import Optional

from ..consensus.block import CBlock, CBlockHeader
from ..consensus.merkle import block_merkle_root
from ..consensus.params import ChainParams, get_block_subsidy
from ..consensus.pow import get_next_work_required
from ..consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from ..consensus.versionbits import compute_block_version
from ..util import telemetry as tm
from ..validation.chain import CBlockIndex
from ..validation.chainstate import ChainstateManager, _script_int

# ISSUE 20: getblocktemplate build-latency breakdown — "select" is the
# mempool package-selection leg (the batched frontier's hot path),
# "total" the whole CreateNewBlock including merkle root + the
# TestBlockValidity dry-run. Under a flood the select leg is the part
# the incremental frontier must keep flat.
_TEMPLATE_H = tm.histogram(
    "bcp_template_build_seconds",
    "CreateNewBlock wall-clock per template",
    labels=("stage",))


def template_build_quantiles() -> dict:
    """gettpuinfo.mempool's template view: p50/p99 (ms) per build stage."""
    out = {}
    for stage in ("select", "total"):
        h = _TEMPLATE_H.labels(stage=stage)
        out[stage] = {f"{k}_ms": round(v * 1e3, 3)
                      for k, v in h.quantiles((0.5, 0.99)).items()}
        out[stage]["count"] = h.count
    return out


def bip34_coinbase_script_sig(height: int, extranonce: int = 0) -> bytes:
    """Height push (BIP34) + extranonce push — the reference's
    IncrementExtraNonce writes CScript() << nHeight << CScriptNum(nExtraNonce)."""
    tail = _script_int(extranonce) if extranonce > 0 else b""
    sig = _script_int(height) + tail
    if len(sig) < 2:  # bad-cb-length lower bound
        sig += b"\x00"
    return sig


@dataclass
class BlockTemplate:
    """CBlockTemplate (src/miner.h): block + per-tx fees/sigops."""

    block: CBlock
    fees: list[int] = field(default_factory=list)
    height: int = 0
    target: int = 0


class BlockAssembler:
    """BlockAssembler (src/miner.cpp:~110)."""

    def __init__(self, chainstate: ChainstateManager, mempool=None,
                 versionbits_cache=None):
        self.chainstate = chainstate
        self.mempool = mempool
        self.params: ChainParams = chainstate.params
        # VersionBitsCache: without it every template re-walks all period
        # boundaries from genesis (O(height) per getblocktemplate)
        self.versionbits_cache = versionbits_cache

    def create_new_block(self, script_pubkey: bytes,
                         time_override: Optional[int] = None) -> BlockTemplate:
        """CreateNewBlock: coinbase + greedy package selection + a
        TestBlockValidity dry-run (the reference asserts its own template
        connects)."""
        # settle barrier: a template is a tip externalization — mining on
        # an unsettled speculative tip would select mempool txs the
        # speculative layer already spent (the mempool only learns of
        # them at settle), assembling an invalid child
        t0 = _time.monotonic()
        settle = getattr(self.chainstate, "settle_horizon", None)
        if settle is not None:
            settle()
        tip = self.chainstate.tip()
        assert tip is not None
        height = tip.height + 1
        consensus = self.params.consensus

        now = self.chainstate.get_time()
        block_time = max(tip.get_median_time_past() + 1, now)
        if time_override is not None:
            block_time = time_override
        bits = get_next_work_required(tip, block_time, consensus)

        txs: list[CTransaction] = []
        fees: list[int] = []
        total_fees = 0
        if self.mempool is not None:
            t_sel = _time.monotonic()
            selected = self.mempool.select_for_block(
                max_size=self.params.max_block_size - 1000,
                height=height,
                block_time=tip.get_median_time_past(),
            )
            _TEMPLATE_H.labels(stage="select").observe(
                _time.monotonic() - t_sel)
            for entry in selected:
                txs.append(entry.tx)
                fees.append(entry.base_fee)
                total_fees += entry.base_fee

        coinbase = CTransaction(
            version=1,
            vin=(CTxIn(COutPoint(), bip34_coinbase_script_sig(height), 0xFFFFFFFF),),
            vout=(CTxOut(total_fees + get_block_subsidy(height, consensus), script_pubkey),),
            locktime=0,
        )
        vtx = (coinbase, *txs)
        root, _ = block_merkle_root(_BlockView(vtx))
        # ComputeBlockVersion (miner.cpp:~60): signal every versionbits
        # deployment currently STARTED/LOCKED_IN on top of TOP_BITS
        version = compute_block_version(
            tip, consensus.deployments,
            consensus.miner_confirmation_window,
            consensus.rule_change_activation_threshold,
            self.versionbits_cache,
        )
        header = CBlockHeader(
            version=version,
            hash_prev_block=tip.hash,
            hash_merkle_root=root,
            time=block_time,
            bits=bits,
            nonce=0,
        )
        block = CBlock(header, vtx)
        from ..consensus.pow import compact_to_target

        target, _bad = compact_to_target(bits)
        tmpl = BlockTemplate(block=block, fees=[0, *fees], height=height, target=target)
        self._test_block_validity(tmpl)
        _TEMPLATE_H.labels(stage="total").observe(_time.monotonic() - t0)
        return tmpl

    def _test_block_validity(self, tmpl: BlockTemplate) -> None:
        """TestBlockValidity (src/validation.cpp:~3500): dry-run the
        non-PoW checks so a bad template never reaches the miner (shared
        with getblocktemplate's BIP22 proposal mode)."""
        self.chainstate.test_block_validity(tmpl.block)


class _BlockView:
    """Minimal duck-typed block for block_merkle_root before CBlock exists."""

    def __init__(self, vtx):
        self.vtx = vtx


def increment_extranonce(block: CBlock, height: int, extranonce: int) -> CBlock:
    """IncrementExtraNonce (src/miner.cpp:~440): bump the coinbase scriptSig
    extranonce and recompute the Merkle root. Returns a new block (immutables
    all the way down); the caller owns the extranonce counter."""
    coinbase = block.vtx[0]
    new_cb = CTransaction(
        version=coinbase.version,
        vin=(
            CTxIn(
                COutPoint(),
                bip34_coinbase_script_sig(height, extranonce),
                coinbase.vin[0].sequence,
            ),
        ),
        vout=coinbase.vout,
        locktime=coinbase.locktime,
    )
    vtx = (new_cb, *block.vtx[1:])
    root, _ = block_merkle_root(_BlockView(vtx))
    return CBlock(replace(block.header, hash_merkle_root=root), vtx)
