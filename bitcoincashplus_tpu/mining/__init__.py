"""Mining: block template assembly + PoW search orchestration.

Reference: src/miner.cpp (BlockAssembler::CreateNewBlock :~130,
addPackageTxs :~300, IncrementExtraNonce :~440) and the generateBlocks RPC
loop (src/rpc/mining.cpp:~120) whose scalar nonce search is replaced by the
TPU sweep (ops/miner, parallel/nonce_shard).
"""

from .assembler import BlockAssembler, BlockTemplate, increment_extranonce
from .generate import generate_blocks

__all__ = [
    "BlockAssembler",
    "BlockTemplate",
    "increment_extranonce",
    "generate_blocks",
]
