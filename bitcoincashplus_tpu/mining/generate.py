"""generatetoaddress / generateBlocks — the mining driver.

Reference: src/rpc/mining.cpp:~120 (generateBlocks): per block, assemble a
template, bump extranonce, then a scalar nonce `while` loop around
CheckProofOfWork. Here the inner loop is the TPU sweep (single-chip
ops/miner.sweep_header, or the multi-chip shard when a mesh is available),
and the mined block feeds back through ProcessNewBlock exactly like the
reference accepting its own block.
"""

from __future__ import annotations

from typing import Optional

from ..consensus.block import CBlock
from ..ops.dispatch import supervised_sweep
from ..ops.miner import DEFAULT_TILE
from ..validation.chainstate import ChainstateManager
from .assembler import BlockAssembler, increment_extranonce

# generateBlocks' nInnerLoopCount is 0x10000 (one extranonce bump per 64Ki
# nonces) in the reference — far too small a stride for a vectorized sweep.
# We sweep the whole 32-bit space per extranonce before bumping.
MAX_TRIES_DEFAULT = 1_000_000  # reference default nMaxTries


def mine_block(assembler: BlockAssembler, script_pubkey: bytes,
               max_tries: int = MAX_TRIES_DEFAULT,
               tile: int = DEFAULT_TILE,
               sweep=None,
               time_override: Optional[int] = None,
               extranonce_start: int = 0) -> Optional[CBlock]:
    """Assemble + PoW-search one block. Returns the mined block or None if
    max_tries hashes were exhausted. `sweep` is injectable (single-chip
    default; parallel.nonce_shard.sweep_header_sharded for a mesh;
    node._select_sweep wires mining/resident.ResidentSweep.sweep — there,
    each extranonce bump below is a device-side template BUFFER SWAP into
    the persistent resident loop, not a fresh dispatch); the
    default is the SUPERVISED single-chip sweep (ops/dispatch): a claimed
    hit is host re-verified and a dead device degrades to the scalar CPU
    loop under the miner circuit breaker.

    ``extranonce_start`` seeds the coinbase extranonce counter: two nodes
    assembling from the same parent with the same payout script and a
    MTP-pinned header time would otherwise mine byte-identical blocks
    (sub-second regtest mining made that collision real — the node layer
    passes per-block entropy; the default 0 keeps unit-test chains
    deterministic)."""
    if sweep is None:
        sweep = supervised_sweep()
    tmpl = assembler.create_new_block(script_pubkey, time_override)
    height, target = tmpl.height, tmpl.target
    block = tmpl.block
    tries_left = max_tries
    extranonce = extranonce_start
    while tries_left > 0:
        extranonce += 1
        block = increment_extranonce(block, height, extranonce)
        nonce, hashes = sweep(
            block.header.serialize(), target,
            max_nonces=min(tries_left, 1 << 32), tile=tile,
        )
        tries_left -= max(hashes, 1)
        if nonce is not None:
            mined = CBlock(block.header.with_nonce(nonce), block.vtx)
            return mined
    return None


def generate_blocks(chainstate: ChainstateManager, script_pubkey: bytes,
                    n_blocks: int, max_tries: int = MAX_TRIES_DEFAULT,
                    mempool=None, tile: int = DEFAULT_TILE,
                    sweep=None) -> list[bytes]:
    """generatetoaddress backend: mine and connect n_blocks, returning their
    hashes (wire order), like the RPC's JSON array of hex hashes."""
    if sweep is None:
        sweep = supervised_sweep()
    assembler = BlockAssembler(chainstate, mempool)
    hashes: list[bytes] = []
    for _ in range(n_blocks):
        block = mine_block(assembler, script_pubkey, max_tries, tile, sweep)
        if block is None:
            break
        chainstate.process_new_block(block)
        hashes.append(block.get_hash())
    return hashes
