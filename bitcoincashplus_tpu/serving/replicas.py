"""Read-replica pool for the fleet serving front door (ISSUE 16).

A replica is an ordinary bcpd process bootstrapped in seconds from a
validator-produced UTXO snapshot (``dumptxoutset`` -> ``loadtxoutset``,
the PR 12 assumeutxo path) and kept at the tip over the existing
compact-block relay. This module owns the *robustness* half of the
story: per-replica health probes, a per-replica circuit breaker reusing
the ops/dispatch discipline (trip on consecutive transport failures,
half-open probes after a cooldown, re-admit on probe success), and the
consistency gate — a replica whose probed tip lags the pool fan-out
height by more than ``max_lag`` is rotated OUT and never served from,
so no reply externalizes state older than the bounded-staleness
contract promises.

Transport is an injectable callable ``(method, params) -> result`` so
the unit suite exercises every rotation/breaker/lag policy without a
single subprocess; the node wires in a thin JSON-RPC HTTP transport
(rpc/client idiom) against real replica processes. Every replica call
passes the ``replica_rpc`` fault site (util/faults.REPLICA_RPC_SITE,
explicit-only) so drills can kill or slow the replica leg on demand.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Callable, Optional, Sequence

from ..ops.dispatch import BreakerConfig, CircuitBreaker
from ..util import telemetry as tm
from ..util.faults import INJECTOR, REPLICA_RPC_SITE
from ..util.log import log_print

_PROBE_C = tm.counter(
    "bcp_gateway_replica_probes_total",
    "Replica health probes by outcome",
    labels=("replica", "outcome"))


class ReplicaError(RuntimeError):
    """Transport-level failure on the replica leg (socket error, timeout,
    malformed reply, injected fault). Method-level JSON-RPC errors are
    NOT wrapped here — they are definitive answers, not replica
    sickness, and must never trigger failover."""


class ReplicaRPCError(RuntimeError):
    """A definitive JSON-RPC error returned by a healthy replica (e.g.
    "Block not found"). Carries the error object so the gateway can
    relay it verbatim instead of failing over."""

    def __init__(self, error: dict):
        super().__init__(str(error.get("message", error)))
        self.error = dict(error)


def http_transport(host: str, port: int, auth_b64: str,
                   timeout: float = 30.0) -> Callable:
    """JSON-RPC-over-HTTP transport to one replica (rpc/client.py shape,
    per-call connection). Raises ReplicaError on any transport failure
    and ReplicaRPCError on a method-level error object."""

    def call(method: str, params: Sequence):
        payload = json.dumps({"jsonrpc": "1.0", "id": 0, "method": method,
                              "params": list(params)})
        try:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
            try:
                conn.request("POST", "/", payload, {
                    "Authorization": f"Basic {auth_b64}",
                    "Content-Type": "application/json",
                })
                body = json.loads(conn.getresponse().read())
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException) as e:
            raise ReplicaError(f"{host}:{port}: {e!r}") from e
        if not isinstance(body, dict):
            raise ReplicaError(f"{host}:{port}: malformed reply")
        if body.get("error"):
            raise ReplicaRPCError(body["error"])
        return body.get("result")

    return call


class Replica:
    """One pool member: a transport, a breaker, and the last probed tip.

    ``in_rotation`` is the pool's serve/don't-serve verdict, refreshed on
    every probe pass: the breaker must be healthy AND the probed tip must
    be within ``max_lag`` of the pool fan-out height."""

    def __init__(self, name: str, transport: Callable,
                 breaker_cfg: Optional[BreakerConfig] = None,
                 clock=time.monotonic):
        self.name = name
        self.transport = transport
        self.breaker = CircuitBreaker(f"replica:{name}", cfg=breaker_cfg,
                                      clock=clock)
        self.tip_height = -1
        self.tip_hash = ""
        self.lagging = False
        # quarantine (ISSUE 17): the replica onboarded from a snapshot
        # whose trust is not yet established — no verified certificate at
        # load AND background validation still running. Pool-visible (it
        # probes, its tip feeds the fan-out height) but shed from serving
        # exactly like a lagging replica, until the probe sees
        # snapshot.certificate_verified flip true.
        self.quarantined = False
        self.quarantine_logged = False
        self.in_rotation = False
        self.last_probe_ok = 0.0
        # call/error tallies are bumped from every gateway handler
        # thread plus the probe loop; += is a read-modify-write tear
        # without this (BCP008)
        self._stats_lock = threading.Lock()
        self.calls = 0
        self.errors = 0

    def call(self, method: str, params: Sequence):
        """One serving call on the replica leg. Transport failures (and
        injected ``replica_rpc`` faults) count against the breaker at the
        CALLER — the gateway records the verdict so a coalesced leader's
        failure is charged exactly once."""
        INJECTOR.on_call(REPLICA_RPC_SITE)
        with self._stats_lock:
            self.calls += 1
        try:
            return self.transport(method, params)
        except ReplicaRPCError:
            raise  # definitive answer — not replica sickness
        except Exception as e:
            with self._stats_lock:
                self.errors += 1
            raise ReplicaError(f"replica {self.name}: {e!r}") from e

    def probe(self) -> bool:
        """Health probe: one getblockchaininfo through the same injected
        leg as serving traffic (a replica that can't serve probes can't
        serve reads either). Updates the probed tip and the breaker."""
        try:
            info = self.call("getblockchaininfo", [])
            self.tip_height = int(info["blocks"])
            self.tip_hash = str(info["bestblockhash"])
            # absent sub-doc = never snapshot-onboarded = nothing to
            # quarantine; present = trust the gate it reports
            snap = info.get("snapshot")
            self.quarantined = bool(
                snap and not snap.get("certificate_verified"))
        except Exception as e:
            self.breaker.record_failure(e)
            _PROBE_C.labels(replica=self.name, outcome="fail").inc()
            return False
        self.breaker.record_success()
        self.last_probe_ok = time.monotonic()
        _PROBE_C.labels(replica=self.name, outcome="ok").inc()
        return True

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            "in_rotation": self.in_rotation,
            "lagging": self.lagging,
            "quarantined": self.quarantined,
            "tip_height": self.tip_height,
            "tip_hash": self.tip_hash,
            "calls": self.calls,
            "errors": self.errors,
            "breaker": self.breaker.snapshot(),
        }


class ReplicaPool:
    """Health-probed, breaker-gated, lag-gated rotation over N replicas.

    ``probe_once()`` is the single source of truth for rotation: it
    probes every replica whose breaker admits a call (OPEN breakers wait
    out their cooldown — the probabilistic half-open probe IS the
    re-admission test), computes the fan-out height as the max of the
    validator tip and every replica tip, and rotates out any replica
    lagging it by more than ``max_lag``. A background thread runs the
    pass every ``probe_interval`` seconds; tests call it directly."""

    def __init__(self, replicas: Sequence[Replica], max_lag: int = 2,
                 probe_interval: float = 0.5,
                 validator_tip: Optional[Callable[[], int]] = None):
        self.replicas = list(replicas)
        self.max_lag = max(0, int(max_lag))
        self.probe_interval = probe_interval
        self.validator_tip = validator_tip
        self.fanout_height = -1
        self.rotations_out = 0     # times a replica left the rotation
        self.quarantines = 0       # rotations-out caused by quarantine
        self._rr = 0               # round-robin cursor
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- probing --------------------------------------------------------

    def probe_once(self) -> None:
        heights = []
        if self.validator_tip is not None:
            try:
                heights.append(int(self.validator_tip()))
            except Exception:
                pass
        for rep in self.replicas:
            if rep.breaker.allow():
                if rep.probe():
                    heights.append(rep.tip_height)
            elif rep.tip_height >= 0:
                heights.append(rep.tip_height)
        self.fanout_height = max(heights) if heights else -1
        for rep in self.replicas:
            rep.lagging = (rep.tip_height < 0 or
                           self.fanout_height - rep.tip_height > self.max_lag)
            admit = (rep.breaker.healthy() and not rep.lagging
                     and not rep.quarantined)
            if rep.in_rotation and not admit:
                self.rotations_out += 1
                log_print("gateway", "replica %s rotated out (lagging=%s "
                          "quarantined=%s breaker=%s tip=%d fanout=%d)",
                          rep.name, rep.lagging, rep.quarantined,
                          rep.breaker.state, rep.tip_height,
                          self.fanout_height)
            elif not rep.in_rotation and admit and rep.quarantine_logged:
                log_print("gateway", "replica %s re-admitted (certificate "
                          "verified, tip=%d)", rep.name, rep.tip_height)
            if rep.quarantined and not rep.quarantine_logged:
                # one per episode, whether the replica was shed from
                # rotation or arrived already-quarantined (a fresh
                # cert-less onboard is an episode too)
                rep.quarantine_logged = True
                self.quarantines += 1
                log_print("gateway", "replica %s QUARANTINED: snapshot "
                          "loaded without verified certificate — shed "
                          "from serving until validation completes",
                          rep.name)
            elif not rep.quarantined:
                rep.quarantine_logged = False
            rep.in_rotation = admit

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            try:
                self.probe_once()
            except Exception:  # the prober itself must not die
                pass

    def start(self) -> None:
        if self._thread is None:
            self.probe_once()
            self._thread = threading.Thread(
                target=self._probe_loop, name="replica-probe", daemon=True)
            self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- selection ------------------------------------------------------

    def pick(self, exclude: Sequence[str] = ()) -> Optional[Replica]:
        """Next in-rotation replica (round-robin), skipping ``exclude``
        (names already tried this request — the failover loop's memory)
        and any replica whose breaker refuses the call right now."""
        if not self.replicas:
            return None
        with self._lock:
            start = self._rr
            for i in range(len(self.replicas)):
                rep = self.replicas[(start + i) % len(self.replicas)]
                if rep.name in exclude or not rep.in_rotation:
                    continue
                if not rep.breaker.allow():
                    continue
                self._rr = (start + i + 1) % len(self.replicas)
                return rep
        return None

    def in_rotation(self) -> list[Replica]:
        return [r for r in self.replicas if r.in_rotation]

    def snapshot(self) -> dict:
        return {
            "fanout_height": self.fanout_height,
            "max_lag": self.max_lag,
            "rotations_out": self.rotations_out,
            "quarantines": self.quarantines,
            "quarantined": sum(1 for r in self.replicas if r.quarantined),
            "replicas": [r.snapshot() for r in self.replicas],
        }
