"""Always-on signing + fleet serving.

The persistent micro-batching SigService (ISSUE 7) generalizes the
pipelined IBD engine's cross-block LanePacker into a serving front-end
for live traffic: mempool acceptance, compact-block reconstruction, and
getblocktemplate re-validation enqueue per-input script checks into
shared device lanes and await per-tx futures.

The fleet front door (ISSUE 16) scales the read path horizontally:
serving/replicas pools snapshot-bootstrapped read replicas behind
health probes, breakers and a lag gate; serving/gateway load-balances
client RPC over them with token-bucket admission, request coalescing
and storm-proof failover.
"""

from .gateway import Gateway  # noqa: F401
from .replicas import Replica, ReplicaPool  # noqa: F401
from .sigservice import (  # noqa: F401
    SigService,
    TxSigFuture,
    prewarm_block_sigs,
)
