"""Always-on signature serving (ISSUE 7).

The persistent micro-batching SigService generalizes the pipelined IBD
engine's cross-block LanePacker into a serving front-end for live
traffic: mempool acceptance, compact-block reconstruction, and
getblocktemplate re-validation enqueue per-input script checks into
shared device lanes and await per-tx futures.
"""

from .sigservice import (  # noqa: F401
    SigService,
    TxSigFuture,
    prewarm_block_sigs,
)
