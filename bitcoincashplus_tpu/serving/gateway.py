"""Fleet serving front door (ISSUE 16): admission, coalescing, failover.

The gateway is a JSON-RPC HTTP server (rpc/server shape) that fronts one
validator and N read replicas (serving/replicas.ReplicaPool):

* **Admission** — per-client token buckets with *graduated* shedding:
  a read-only query must leave a reserve of its client's bucket for
  tip-critical traffic (submit/send/template), and the global in-flight
  ceiling sheds read-only at the soft limit long before tip-critical
  hits the hard limit. Every reject is a metered, 429-style JSON-RPC
  error (``GATEWAY_OVERLOADED``) — never a silent drop.
* **Coalescing** — identical in-flight ``getblock``/``gettxout``/
  ``getblocktemplate``-class queries collapse to ONE backend call via
  the SigService dedup pattern (in-flight table keyed by method+params,
  followers rendezvous on the leader's condvar).
* **Failover** — read queries round-robin over the replica rotation;
  a transport failure records against that replica's breaker and the
  *idempotent* read retries on the next healthy replica after a
  jittered ``util/faults.Backoff`` pause, falling back to the validator
  when the rotation is exhausted. Method-level RPC errors are
  definitive answers and relay verbatim (no failover).
* **Consistency gate** — the gateway only ever picks replicas the pool
  keeps in rotation, and the pool rotates out anything lagging the
  fan-out height beyond ``-maxreplicalag`` (replicas.ReplicaPool).

Fault site ``gateway`` (util/faults.GATEWAY_SITE, explicit-only) fires
at the admission boundary; ``replica_rpc`` fires on every replica leg
(serving/replicas.Replica.call).

Telemetry: native ``bcp_gateway_*`` families below plus a registry
collector projecting per-replica breaker state — unregistered in
``close()`` so a test-scoped gateway never leaks into later scrapes.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional, Sequence

from ..util import telemetry as tm
from ..util.faults import GATEWAY_SITE, INJECTOR, Backoff
from ..util.log import log_print, log_printf
from .replicas import ReplicaPool, ReplicaRPCError

# 429-style JSON-RPC reject (the HTTP layer also sets status 429)
GATEWAY_OVERLOADED = -429

# Read-only queries a bounded-staleness replica may answer. Mempool views
# are deliberately absent: replica mempools are independent, so anything
# mempool-shaped stays on the validator.
READ_METHODS = frozenset({
    "getblock", "getblockhash", "getblockcount", "getbestblockhash",
    "getblockheader", "getblockchaininfo", "gettxout", "gettxoutsetinfo",
    "getdifficulty", "getchaintips", "getblockstats",
})

# Identical in-flight queries that collapse to one backend call.
# getblocktemplate is validator-bound but the most expensive read on the
# box — exactly the call a thundering herd of miners duplicates.
COALESCE_METHODS = READ_METHODS | {"getblocktemplate"}

_ADMIT_C = tm.counter(
    "bcp_gateway_admitted_total",
    "Requests admitted past the gateway's token-bucket/overload gates",
    labels=("cls",))
_SHED_C = tm.counter(
    "bcp_gateway_sheds_total",
    "Requests shed (429-style reject) by traffic class and reason "
    "(rate = client token bucket, overload = global in-flight ceiling)",
    labels=("cls", "reason"))
_COAL_C = tm.counter(
    "bcp_gateway_coalesce_hits_total",
    "Requests served as followers of an identical in-flight query "
    "(one backend call fanned out to N clients)")
_FAIL_C = tm.counter(
    "bcp_gateway_failovers_total",
    "Mid-request failovers: a replica leg failed and the idempotent "
    "read retried on another backend")
_VFB_C = tm.counter(
    "bcp_gateway_validator_fallback_total",
    "Read queries served by the validator because the replica rotation "
    "was empty or exhausted")
_LAT_H = tm.histogram(
    "bcp_gateway_latency_seconds",
    "Gateway request latency by traffic class (admission to reply)",
    labels=("cls",))

_BREAKER_STATE_NUM = {"closed": 0, "half-open": 1, "open": 2}


class GatewayReject(RuntimeError):
    """Admission reject — maps to a 429-style JSON-RPC error."""

    def __init__(self, message: str):
        super().__init__(message)
        self.code = GATEWAY_OVERLOADED


class BackendRPCError(RuntimeError):
    """Definitive JSON-RPC error from a backend — relayed verbatim."""

    def __init__(self, error: dict):
        super().__init__(str(error.get("message", error)))
        self.error = dict(error)


class TokenBucket:
    """Classic token bucket with a *floor*: ``take(n, floor=f)`` refuses
    to spend below ``f`` tokens — how read-only traffic is made to leave
    a reserve for tip-critical calls from the same client."""

    __slots__ = ("capacity", "rate", "tokens", "stamp")

    def __init__(self, capacity: float, rate: float, now: float):
        self.capacity = float(capacity)
        self.rate = float(rate)
        self.tokens = float(capacity)
        self.stamp = now

    def take(self, n: float, floor: float, now: float) -> bool:
        if now > self.stamp:
            self.tokens = min(self.capacity,
                              self.tokens + (now - self.stamp) * self.rate)
            self.stamp = now
        if self.tokens - n >= floor:
            self.tokens -= n
            return True
        return False


class _Flight:
    """One in-flight coalesced query (SigService _Lane shape): the leader
    executes, followers wait on the condvar and share the settled result
    or exception."""

    __slots__ = ("cv", "done", "result", "error", "followers")

    def __init__(self, lock: threading.Lock):
        self.cv = threading.Condition(lock)
        self.done = False
        self.result = None
        self.error: Optional[BaseException] = None
        self.followers = 0


class Coalescer:
    """In-flight request dedup (the SigService ``_by_key`` pattern)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_key: dict[str, _Flight] = {}

    def run(self, key: str, fn: Callable) -> tuple[object, bool]:
        """Execute ``fn`` once per distinct in-flight ``key``; returns
        ``(result, follower)`` where follower=True means this call rode
        an identical leader's backend call."""
        with self._lock:
            fl = self._by_key.get(key)
            if fl is None:
                fl = self._by_key[key] = _Flight(self._lock)
                leader = True
            else:
                fl.followers += 1
                leader = False
        if leader:
            try:
                fl.result = fn()
            except BaseException as e:
                fl.error = e
            finally:
                with self._lock:
                    fl.done = True
                    self._by_key.pop(key, None)
                    fl.cv.notify_all()
        else:
            with self._lock:
                while not fl.done:
                    fl.cv.wait()
        if fl.error is not None:
            raise fl.error
        return fl.result, not leader


class Gateway:
    """The front door. ``backend`` is the validator call path (method,
    params) -> result, raising BackendRPCError for method-level errors;
    ``pool`` is the replica rotation. Construct + ``handle()`` directly
    in unit tests; ``start()`` binds the HTTP server for real fleets."""

    MAX_CLIENTS = 4096  # bounded LRU of per-client token buckets

    def __init__(self, backend: Callable, pool: ReplicaPool,
                 rate: float = 500.0, burst: float = 200.0,
                 read_reserve: float = 0.25,
                 soft_inflight: int = 64, hard_inflight: int = 256,
                 bind: str = "127.0.0.1", port: int = 0,
                 auth_b64: str = "", clock=time.monotonic,
                 backoff_base: float = 0.01, backoff_max: float = 0.2):
        self.backend = backend
        self.pool = pool
        self.rate = float(rate)
        self.burst = float(burst)
        self.read_reserve = float(read_reserve)
        self.soft_inflight = int(soft_inflight)
        self.hard_inflight = int(hard_inflight)
        self._bind, self._port_req = bind, port
        self._auth = auth_b64
        self._clock = clock
        self._backoff_base, self._backoff_max = backoff_base, backoff_max
        self._coalescer = Coalescer()
        self._adm_lock = threading.Lock()
        self._buckets: OrderedDict[str, TokenBucket] = OrderedDict()
        self._inflight = 0
        self._stats_lock = threading.Lock()
        self.stats = {
            "admitted": {"read": 0, "tip": 0},
            "sheds": {"read": 0, "tip": 0},
            "coalesce_hits": 0,
            "failovers": 0,
            "validator_fallback": 0,
            "requests": 0,
        }
        self._httpd = None
        self._thread = None
        self.port = 0
        self._collector_name = f"gateway:{id(self):x}"
        tm.register_collector(self._collector_name, self._collect)

    # -- telemetry ------------------------------------------------------

    def _collect(self):
        """Scrape-time projection of the replica rotation: breaker state,
        probed tip, and in-rotation flag per replica. Family names are
        disjoint from the native bcp_gateway_* counters above (BCP001)."""
        state = {"name": "bcp_gateway_replica_state", "type": "gauge",
                 "help": "Replica breaker state "
                         "(0=closed 1=half-open 2=open)", "samples": []}
        rot = {"name": "bcp_gateway_replica_in_rotation", "type": "gauge",
               "help": "1 when the replica is served from", "samples": []}
        tip = {"name": "bcp_gateway_replica_tip_height", "type": "gauge",
               "help": "Last probed replica tip height", "samples": []}
        quar = {"name": "bcp_gateway_replica_quarantined", "type": "gauge",
                "help": "1 while the replica is shed for serving an "
                        "unverified snapshot (certificate quarantine)",
                "samples": []}
        infl = {"name": "bcp_gateway_inflight", "type": "gauge",
                "help": "Requests currently inside the gateway",
                "samples": [({}, self._inflight)]}
        for rep in self.pool.replicas:
            lbl = {"replica": rep.name}
            state["samples"].append(
                (lbl, _BREAKER_STATE_NUM.get(rep.breaker.state, -1)))
            rot["samples"].append((lbl, 1 if rep.in_rotation else 0))
            tip["samples"].append((lbl, rep.tip_height))
            quar["samples"].append((lbl, 1 if rep.quarantined else 0))
        return [state, rot, tip, quar, infl]

    # -- admission ------------------------------------------------------

    def _bucket_for(self, client: str, now: float) -> TokenBucket:
        b = self._buckets.get(client)
        if b is None:
            b = self._buckets[client] = TokenBucket(self.burst, self.rate,
                                                    now)
            while len(self._buckets) > self.MAX_CLIENTS:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(client)
        return b

    def _admit(self, cls: str, client: str) -> None:
        """Token-bucket + overload gate; raises GatewayReject on shed.
        Graduated: read-only sheds at the soft in-flight ceiling and
        must leave ``read_reserve`` of its bucket; tip-critical runs to
        the hard ceiling and may drain its bucket to zero."""
        now = self._clock()
        with self._adm_lock:
            ceiling = (self.soft_inflight if cls == "read"
                       else self.hard_inflight)
            if self._inflight >= ceiling:
                self._shed(cls, "overload")
            floor = self.burst * self.read_reserve if cls == "read" else 0.0
            if not self._bucket_for(client, now).take(1.0, floor, now):
                self._shed(cls, "rate")
            self._inflight += 1
        _ADMIT_C.labels(cls=cls).inc()
        with self._stats_lock:
            self.stats["admitted"][cls] += 1

    def _shed(self, cls: str, reason: str) -> None:
        _SHED_C.labels(cls=cls, reason=reason).inc()
        with self._stats_lock:
            self.stats["sheds"][cls] += 1
        raise GatewayReject(
            f"gateway overloaded — request shed (class={cls}, "
            f"reason={reason}); retry with backoff")

    # -- serving --------------------------------------------------------

    def handle(self, method: str, params: Sequence, client: str = ""):
        """One admitted-or-shed request, start to finish. Raises
        GatewayReject (shed), BackendRPCError (definitive RPC error) or
        propagates transport/injected failures after every failover and
        the validator fallback are exhausted."""
        t0 = time.monotonic()
        cls = "read" if method in READ_METHODS else "tip"
        INJECTOR.on_call(GATEWAY_SITE)
        self._admit(cls, client)
        try:
            with self._stats_lock:
                self.stats["requests"] += 1
            if method in COALESCE_METHODS:
                key = method + ":" + json.dumps(
                    list(params), sort_keys=True, default=str)
                result, follower = self._coalescer.run(
                    key, lambda: self._dispatch(method, params, cls))
                if follower:
                    _COAL_C.inc()
                    with self._stats_lock:
                        self.stats["coalesce_hits"] += 1
                return result
            return self._dispatch(method, params, cls)
        finally:
            with self._adm_lock:
                self._inflight -= 1
            _LAT_H.labels(cls=cls).observe(time.monotonic() - t0)

    def _dispatch(self, method: str, params: Sequence, cls: str):
        if cls == "read":
            return self._serve_read(method, params)
        return self.backend(method, params)

    def _serve_read(self, method: str, params: Sequence):
        """Replica rotation with mid-request failover. Reads are
        idempotent by construction (READ_METHODS), so retrying the same
        query on another replica is always safe."""
        tried: list[str] = []
        boff = Backoff(base=self._backoff_base, maximum=self._backoff_max)
        last: Optional[BaseException] = None
        for _ in range(len(self.pool.replicas)):
            rep = self.pool.pick(exclude=tried)
            if rep is None:
                break
            try:
                result = rep.call(method, params)
            except ReplicaRPCError as e:
                # the replica ANSWERED — an RPC-level error is a healthy
                # reply, relayed verbatim, never failed over
                rep.breaker.record_success()
                raise BackendRPCError(e.error) from e
            except Exception as e:
                rep.breaker.record_failure(e)
                tried.append(rep.name)
                last = e
                _FAIL_C.inc()
                with self._stats_lock:
                    self.stats["failovers"] += 1
                log_print("gateway", "read %s failed on replica %s (%r) — "
                          "failing over", method, rep.name, e)
                time.sleep(boff.next())
                continue
            rep.breaker.record_success()
            return result
        # rotation empty or exhausted: the validator serves the read
        _VFB_C.inc()
        with self._stats_lock:
            self.stats["validator_fallback"] += 1
        if last is not None:
            log_print("gateway", "read %s: rotation exhausted — validator "
                      "fallback", method)
        return self.backend(method, params)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Bind the HTTP front door and start the pool's probe loop."""
        from http.server import ThreadingHTTPServer

        self.pool.start()
        self._httpd = ThreadingHTTPServer(
            (self._bind, self._port_req), _make_handler(self))
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="gateway", daemon=True)
        self._thread.start()
        log_printf("Gateway listening on %s:%d (%d replicas)",
                   self._bind, self.port, len(self.pool.replicas))

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.pool.close()
        # the PR 6 lesson: a scrape after close must not see this gateway
        tm.REGISTRY.unregister_collector(self._collector_name)

    def snapshot(self) -> dict:
        with self._stats_lock:
            stats = json.loads(json.dumps(self.stats))
        return {
            **stats,
            "inflight": self._inflight,
            "port": self.port,
            "pool": self.pool.snapshot(),
        }

    # -- HTTP request execution ----------------------------------------

    def execute(self, request: dict, client: str) -> dict:
        """One JSON-RPC call object to one response object (RPCServer
        .execute shape, with the gateway's admission/failover wrapped
        around the dispatch)."""
        req_id = request.get("id")
        method = request.get("method")
        params = request.get("params") or []
        if not isinstance(method, str) or not isinstance(params, list):
            return _error_obj(req_id, -32600, "Invalid Request")
        try:
            result = self.handle(method, params, client)
        except GatewayReject as e:
            return _error_obj(req_id, e.code, str(e))
        except BackendRPCError as e:
            return {"result": None, "error": e.error, "id": req_id}
        except Exception as e:
            log_printf("gateway internal error in %s: %r", method, e)
            return _error_obj(req_id, -32603, f"gateway error: {e}")
        return {"result": result, "error": None, "id": req_id}


def _error_obj(req_id, code: int, message: str) -> dict:
    return {"result": None,
            "error": {"code": code, "message": message}, "id": req_id}


def _make_handler(gw: Gateway):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log_print("gateway", "http: " + fmt, *args)

        def _reply(self, status: int, payload: bytes) -> None:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_POST(self):
            if gw._auth and \
                    self.headers.get("Authorization") != f"Basic {gw._auth}":
                self.send_response(401)
                self.send_header("WWW-Authenticate",
                                 'Basic realm="gateway"')
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            client = self.headers.get("X-Client-Id") \
                or self.client_address[0]
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length))
            except (ValueError, json.JSONDecodeError):
                self._reply(500, json.dumps(
                    _error_obj(None, -32700, "Parse error")).encode())
                return
            if isinstance(body, list):
                response = [gw.execute(req, client) for req in body]
                status = 200
            else:
                response = gw.execute(body, client)
                err = response.get("error")
                status = 429 if err \
                    and err["code"] == GATEWAY_OVERLOADED else 200
            self._reply(status, json.dumps(response).encode())

    return Handler
