"""SigService — a persistent, deadline-driven micro-batching signature
verification service for the live-traffic hot path.

The IBD graft (ops/ecdsa_batch.LanePacker) batches signatures across
in-flight *blocks*; a node serving heavy live traffic is instead
dominated by mempool ingest and tip relay, where work arrives as a
stream of single transactions. This service is the always-on analogue:
callers (mempool/accept.verify_tx_scripts, compact-block reconstruction,
getblocktemplate proposal re-validation) enqueue per-input
SigCheckRecords into a shared lane buffer and await per-tx futures; a
dedicated service thread flushes lanes into ops/ecdsa_batch dispatches.

Flush policy — a bucket flush fires on the FIRST of:
  * full      — pending lanes reached the bucket target (-sigservicelanes)
  * deadline  — the oldest pending lane aged past -sigservicedeadline,
                so a lone transaction never starves waiting for peers
  * kick      — a caller blocked in TxSigFuture.result() with lanes still
                parked; batching only ever helps *concurrent* callers, so
                a blocked waiter flushes immediately rather than paying
                the deadline for nothing
  * stop      — service shutdown drains whatever is pending

Sigcache awareness: records whose (sighash, r, s, pubkey) key is already
cached never occupy a lane (the future resolves them to True inline), and
identical records submitted concurrently share ONE lane (in-flight dedup
by key — a relay storm delivering the same signature through several
paths verifies it once). Settled TRUE verdicts are inserted into the
shared SignatureCache at settle time, so service-verified mempool inputs
are cache hits for the eventual block connect — exactly what the
synchronous path guaranteed.

Degradation: every flush goes through ecdsa_batch.dispatch_batch, i.e.
the supervised device-decompose -> host-decompose -> w4 -> XLA -> CPU
chain with breaker/KAT gating. A flush that raises anyway resolves the
affected lanes to an error state and TxSigFuture.result() re-verifies
those records on the CPU oracle — the verdict a caller sees is never
dropped or fabricated, and ``-sigservice=off`` is byte-identical by
construction (the callers run the unchanged synchronous path).

Since ISSUE 11 the GLV lattice split rides the device program, so the
host half of a flush (_dispatch_flush) is numpy byte emission only: with
``-sigservicebuffers`` >= 2 the residual emit of flush N+1 overlaps the
device decompose+verify of flush N. (The BENCH_r11 re-measure of the
closed-loop ``concurrent`` level still favors sync — 0.33x — which
rules pack cost OUT as the cause: bounded concurrency simply cannot
fill buckets, so the batching tax is structural there, not a host leg.)

Block-import priority: while a block is being connected
(ChainstateManager wraps process_new_block* in ``import_priority()``),
mempool flushes dispatch on the CPU lane so the settle horizon keeps the
device to itself.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional, Sequence

import numpy as np

from ..ops import ecdsa_batch
from ..util import devicewatch as dw
from ..util import lockwatch
from ..util import telemetry as tm
from ..util.log import log_printf
from ..validation.sigcache import SignatureCache

# Flush-policy defaults: 2046 lanes fill the 2048 compiled bucket exactly
# once the supervised dispatch appends its 2 known-answer lanes (the same
# sizing as LanePacker); 4 ms keeps a lone tx's worst-case added latency
# well under any human-visible budget while still letting a burst batch.
DEFAULT_LANES = 2046
DEFAULT_DEADLINE_MS = 4.0
# TxSigFuture.result() safety net: if the service thread is wedged past
# this, the caller re-verifies its own records on the CPU oracle.
RESULT_TIMEOUT_S = 30.0

FLUSH_REASONS = ("full", "deadline", "kick", "stop")

# -- telemetry families (util/telemetry) --------------------------------
_QUEUE_G = tm.gauge(
    "bcp_sigservice_queue_depth",
    "Signature lanes parked in the SigService pending buffer")
_FLUSH_C = tm.counter(
    "bcp_sigservice_flush_total",
    "SigService bucket flushes by firing policy",
    labels=("reason",))
_FLUSH_LANES_H = tm.histogram(
    "bcp_sigservice_flush_lanes",
    "Real lanes per SigService flush",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 512, 1024, 2046, 4096))
_WAIT_H = tm.histogram(
    "bcp_sigservice_wait_seconds",
    "Enqueue -> settled verdict latency per lane",
    buckets=(0.0005, 0.001, 0.002, 0.004, 0.008, 0.016, 0.032, 0.064,
             0.128, 0.25, 0.5, 1.0, 5.0))
_MISS_C = tm.counter(
    "bcp_sigservice_deadline_miss_total",
    "Flushes that fired later than 2x the configured deadline")


class _Lane:
    """One pending signature check: the record, its sigcache key, and the
    settle verdict every subscribed future shares. The rendezvous is the
    SERVICE's condition variable (one notify_all per flush), not a
    per-lane Event — Event allocation alone cost ~12 µs/lane, which at
    storm rates was a double-digit share of the whole submit path."""

    __slots__ = ("record", "key", "t_enqueue", "ctx", "ok", "err")

    def __init__(self, record, key: bytes, ctx):
        self.record = record
        self.key = key
        self.t_enqueue = time.monotonic()
        self.ctx = ctx  # enqueue-side trace context (flush span parent)
        self.ok: Optional[bool] = None
        self.err: Optional[BaseException] = None

    def settled(self) -> bool:
        return self.ok is not None or self.err is not None


class TxSigFuture:
    """One caller's slice of the shared lanes. ``sources`` holds, per
    submitted record in order: True (pre-settled — sigcache hit) or a
    _Lane (possibly shared with other futures via in-flight dedup)."""

    __slots__ = ("_service", "_sources")

    def __init__(self, service: "SigService", sources: list):
        self._service = service
        self._sources = sources

    def done(self) -> bool:
        return all(s is True or s.settled() for s in self._sources)

    def wait(self, timeout: float) -> bool:
        """Advisory barrier: kick, then block until every lane settles or
        ``timeout`` elapses; returns whether everything settled. Never
        re-verifies anything itself — callers that only want the settle
        side effects (prewarm_block_sigs warming the sigcache) use this
        instead of result(), so a backlogged service costs them at most
        the timeout, never a serial CPU re-verify under their locks (the
        service still settles the lanes later and the cache still fills)."""
        lanes = [s for s in self._sources if s is not True]
        if not any(not lane.settled() for lane in lanes):
            return True
        self._service.kick()
        deadline = time.monotonic() + timeout
        cond = self._service._cond
        with cond:
            while any(not lane.settled() for lane in lanes):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                cond.wait(remaining)
        return True

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until every lane settles; returns a bool verdict per
        record in submission order. Kicks the service first — a blocked
        waiter must never sit out the deadline when nothing else is
        coming.

        Lanes that timed out or errored are re-verified on the CPU
        oracle by THIS thread (the verdict is never dropped or
        fabricated) — in ONE batched call, with the sigcache consulted
        first and TRUE verdicts inserted after, so futures sharing a
        deduped errored lane pay the re-verify once between them and the
        eventual block connect still gets its cache hit."""
        if timeout is None:
            timeout = self._service.result_timeout
        self.wait(timeout)
        out = np.empty(len(self._sources), dtype=bool)
        unresolved: list[tuple[int, _Lane]] = []
        for i, src in enumerate(self._sources):
            if src is True:
                out[i] = True
            elif src.err is not None or not src.settled():
                if not src.settled():
                    self._service._note_timeout()
                unresolved.append((i, src))
            else:
                out[i] = bool(src.ok)
        if unresolved:
            svc = self._service
            todo: list[tuple[int, _Lane]] = []
            for i, src in unresolved:
                if svc.sigcache is not None and svc.sigcache.contains(
                        src.key):
                    out[i] = True  # another waiter already re-verified it
                else:
                    todo.append((i, src))
            if todo:
                ok = ecdsa_batch.verify_batch(
                    [src.record for _, src in todo], backend="cpu")
                for (i, src), good in zip(todo, ok):
                    out[i] = bool(good)
                    if good and svc.sigcache is not None:
                        svc.sigcache.add(src.key)
        return out


class SigService:
    """The always-on micro-batching verify loop (module docstring)."""

    def __init__(self, sigcache: Optional[SignatureCache] = None,
                 backend: str = "auto", kernel: Optional[str] = None,
                 deadline_ms: float = DEFAULT_DEADLINE_MS,
                 lanes: int = DEFAULT_LANES,
                 watchdog_quiet: Optional[float] = None,
                 buffers: int = 2):
        if deadline_ms < 0:
            raise ValueError(
                f"-sigservicedeadline={deadline_ms}: must be >= 0")
        if lanes < 1:
            raise ValueError(f"-sigservicelanes={lanes}: must be >= 1")
        if buffers < 1:
            raise ValueError(f"-sigservicebuffers={buffers}: must be >= 1")
        self.sigcache = sigcache
        self.backend = backend
        self.kernel = kernel
        self.deadline_s = deadline_ms / 1e3
        self.lanes = lanes
        # stall-watchdog quiet period (util/devicewatch; -watchdogquiet):
        # None = env/default, <= 0 = detection off for this subsystem
        self.watchdog_quiet = watchdog_quiet
        self.result_timeout = RESULT_TIMEOUT_S
        # flush double-buffering (-sigservicebuffers, ISSUE 9 / ROADMAP
        # PR 7 headroom): up to ``buffers`` dispatched-but-unsettled
        # flushes ride concurrently, so the host packs flush N+1 while
        # the device verifies flush N. 1 = the PR 7 single-slot loop.
        self.buffers = buffers
        # condition over a (possibly lockwatch-watched) lock: submitters,
        # the flush thread, and settle callbacks all rendezvous here
        self._cond = lockwatch.watched_condition("sigservice_cond")
        self._pending: list[_Lane] = []
        self._by_key: dict[bytes, _Lane] = {}  # pending + in-flight lanes
        self._inflight: list[dict] = []  # dispatched, unsettled flushes
        self._kick = False
        self._stop = False
        self._priority = 0  # block-import preemption depth (re-entrant)
        self._thread: Optional[threading.Thread] = None
        self.stats = {
            "submits": 0, "lanes_enqueued": 0, "cache_hits": 0,
            "dedup_hits": 0, "dispatches": 0, "lanes_real": 0,
            "flush_full": 0, "flush_deadline": 0, "flush_kick": 0,
            "flush_stop": 0, "preempted_dispatches": 0,
            "deadline_misses": 0, "timeouts": 0, "flush_errors": 0,
            "prewarm_txs": 0, "prewarm_records": 0,
            "overlapped_flushes": 0,
        }

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "SigService":
        self._thread = threading.Thread(
            target=self._run, name="sigservice", daemon=True)
        self._thread.start()
        # no-progress sentinel (observe-only): pending lanes with no
        # flush completion for the quiet period = a wedged flush thread
        # (len() is GIL-atomic — the probe must never take the condvar)
        dw.WATCHDOG.register("sigservice",
                             pending_fn=lambda: len(self._pending),
                             quiet_s=self.watchdog_quiet)
        return self

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self) -> None:
        """Drain pending lanes (reason 'stop') and join the thread."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=self.result_timeout)
            self._thread = None
        dw.WATCHDOG.unregister("sigservice")

    # -- enqueue side ---------------------------------------------------

    def submit(self, records: Sequence, keys: Optional[Sequence[bytes]]
               = None) -> TxSigFuture:
        """Enqueue one transaction's fresh sigcheck records; returns the
        per-tx future. Sigcache hits and in-flight duplicates never
        occupy a lane."""
        if keys is None:
            keys = [SignatureCache.entry_key(
                        r.msg_hash, r.r, r.s, r.pubkey,
                        getattr(r, "algo", "ecdsa"))
                    for r in records]
        ctx = tm.trace_context()
        sources: list = []
        fresh = 0
        with self._cond:
            st = self.stats
            st["submits"] += 1
            for rec, key in zip(records, keys):
                if self.sigcache is not None and self.sigcache.contains(key):
                    st["cache_hits"] += 1
                    sources.append(True)
                    continue
                lane = self._by_key.get(key)
                if lane is not None:
                    st["dedup_hits"] += 1
                    if self.sigcache is not None:
                        self.sigcache.note_dedup()
                    sources.append(lane)
                    continue
                lane = _Lane(rec, key, ctx)
                self._by_key[key] = lane
                self._pending.append(lane)
                sources.append(lane)
                fresh += 1
            st["lanes_enqueued"] += fresh
            _QUEUE_G.set(len(self._pending))
            if fresh:
                # always wake the loop: a first lane re-arms the deadline
                # timer (the thread may be parked in an unbounded wait)
                self._cond.notify_all()
        if fresh and not self.running():
            # no service thread (stopped, or it died on a programming
            # error): the flush runs inline on the caller — synchronous,
            # but never stranded
            self._flush_once("kick")
        return TxSigFuture(self, sources)

    def kick(self) -> None:
        """Request an immediate flush (a caller is blocked on a verdict)."""
        with self._cond:
            if not self._pending:
                return
            self._kick = True
            self._cond.notify_all()
        if not self.running():
            self._flush_once("kick")

    def _note_timeout(self) -> None:
        with self._cond:
            self.stats["timeouts"] += 1

    @contextmanager
    def import_priority(self):
        """Block-import preemption: while held, flushes dispatch on the
        CPU lane so the settle horizon keeps the device lanes. Re-entrant
        (nested block connects during a reorg)."""
        with self._cond:
            self._priority += 1
        try:
            yield
        finally:
            with self._cond:
                self._priority -= 1

    # -- service loop ---------------------------------------------------

    def _flush_reason_locked(self) -> Optional[str]:
        if not self._pending:
            self._kick = False  # nothing to kick for
            return None
        if self._stop:
            return "stop"
        if len(self._pending) >= self.lanes:
            return "full"
        if self._kick:
            return "kick"
        age = time.monotonic() - self._pending[0].t_enqueue
        if age >= self.deadline_s:
            return "deadline"
        return None

    def _run(self) -> None:
        try:
            while True:
                settle_now = None
                with self._cond:
                    while True:
                        reason = self._flush_reason_locked()
                        if (reason is not None
                                and len(self._inflight) < self.buffers):
                            break  # a slot is free: go pack + dispatch
                        if self._inflight:
                            # nothing new to pack (or slots full): settle
                            # the OLDEST in-flight flush — its device work
                            # has had the whole pack window to run
                            settle_now = self._inflight.pop(0)
                            break
                        if self._stop:
                            return  # drained: exit
                        timeout = None
                        if self._pending:
                            age = (time.monotonic()
                                   - self._pending[0].t_enqueue)
                            timeout = max(0.0, self.deadline_s - age)
                        self._cond.wait(timeout)
                if settle_now is not None:
                    self._settle_flush(settle_now)
                    continue
                ent = self._dispatch_flush(reason)
                if ent is not None:
                    if self._inflight:
                        # flush N is still on the device while N+1's host
                        # pack just ran — the double-buffer overlap
                        self.stats["overlapped_flushes"] += 1
                    self._inflight.append(ent)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — visible death, below
            # _settle_flush re-raises programming errors AFTER resolving
            # the affected lanes; the thread dies loudly and later
            # submits/kicks run their flushes inline on the caller.
            # Any OTHER in-flight flush's lanes resolve to the same error
            # NOW — waiters must fail fast to their CPU re-verify, not
            # sit out the full result timeout on a dead thread.
            with self._cond:
                for ent in self._inflight:
                    for lane in ent["batch"]:
                        if not lane.settled():
                            lane.err = e
                        self._by_key.pop(lane.key, None)
                self._inflight.clear()
                self._cond.notify_all()
            log_printf("sigservice thread died: %s: %s — submissions "
                       "degrade to inline synchronous dispatch",
                       type(e).__name__, str(e)[:200])

    def _flush_once(self, reason: str) -> None:
        """Pack, dispatch, settle and fulfill ONE bucket synchronously —
        the inline path for callers whose service thread is stopped or
        dead (the thread itself runs the split _dispatch_flush /
        _settle_flush pair through the double-buffer loop)."""
        ent = self._dispatch_flush(reason)
        if ent is not None:
            self._settle_flush(ent)

    def _dispatch_flush(self, reason: str) -> Optional[dict]:
        """The HOST half of a flush: take one bucket off the pending
        buffer, pack, and enqueue the supervised dispatch. The device
        (on an async backend) verifies in the background; the verdict
        wait and lane fulfillment happen in _settle_flush. Returns the
        in-flight entry, or None when nothing was pending."""
        with self._cond:
            if not self._pending:
                return None
            # always cap a flush at the bucket target: an overload burst
            # must not compile a one-off giant bucket — it drains as a
            # train of full buckets (the loop re-fires immediately)
            take = min(len(self._pending), self.lanes)
            batch = self._pending[:take]
            del self._pending[:take]
            if reason in ("kick", "stop"):
                self._kick = False
            st = self.stats
            st[f"flush_{reason}"] = st.get(f"flush_{reason}", 0) + 1
            st["dispatches"] += 1
            st["lanes_real"] += len(batch)
            preempted = self._priority > 0
            if preempted:
                st["preempted_dispatches"] += 1
            age = time.monotonic() - batch[0].t_enqueue
            missed = (self.deadline_s > 0
                      and age > 2.0 * self.deadline_s
                      and reason in ("deadline", "stop"))
            if missed:
                st["deadline_misses"] += 1
            _QUEUE_G.set(len(self._pending))
        _FLUSH_C.labels(reason=reason).inc()
        _FLUSH_LANES_H.observe(len(batch))
        if missed:
            _MISS_C.inc()
            tm.instant("serving.deadline_miss",
                       age_ms=round(age * 1e3, 3),
                       deadline_ms=round(self.deadline_s * 1e3, 3),
                       lanes=len(batch))
        backend = "cpu" if preempted else self.backend
        records = [lane.record for lane in batch]
        handle = err = None
        ctx = None
        with tm.span("serving.flush", parent=batch[0].ctx, reason=reason,
                     lanes=len(batch)):
            # the settle span (possibly on a later loop iteration) chains
            # off this flush span — the same flush->settle structure
            # trace_view reads, just no longer forced to nest in time
            ctx = tm.trace_context()
            try:
                handle = ecdsa_batch.dispatch_batch(
                    records, backend=backend, kernel=self.kernel)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — resolved at settle
                err = e
        return {"batch": batch, "handle": handle, "err": err, "ctx": ctx}

    def _settle_flush(self, ent: dict) -> None:
        """The SETTLE half: block on the dispatch's verdict, fulfill the
        lanes, and broadcast ONCE on the service condvar (the PR 7
        single-notify rendezvous — per-lane Events were the submit-path
        cost the service was built to avoid)."""
        batch = ent["batch"]
        ok, err = None, ent["err"]
        if err is None:
            with tm.span("serving.settle", parent=ent["ctx"],
                         lanes=len(batch)):
                try:
                    ok = ent["handle"].result()
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:  # noqa: BLE001 — waiters parked
                    err = e
        now = time.monotonic()
        with self._cond:
            for i, lane in enumerate(batch):
                if ok is not None:
                    lane.ok = bool(ok[i])
                    if lane.ok and self.sigcache is not None:
                        # settle-side sigcache population: service-verified
                        # inputs must be cache hits for the eventual block
                        # connect, exactly like the synchronous path
                        self.sigcache.add(lane.key)
                else:
                    lane.err = err
                self._by_key.pop(lane.key, None)
                _WAIT_H.observe(now - lane.t_enqueue)
            if err is not None:
                self.stats["flush_errors"] += 1
            self._cond.notify_all()  # one settle broadcast per flush
        # progress beat even on an errored flush: the lanes were resolved
        # (to err) and the thread is demonstrably still draining work —
        # the watchdog watches for NO progress, not for failures
        dw.WATCHDOG.beat("sigservice")
        if err is not None:
            log_printf("sigservice flush failed (%s: %s) — %d lane(s) "
                       "degrade to caller-side CPU re-verify",
                       type(err).__name__, str(err)[:160], len(batch))
            if isinstance(err, (NameError, AttributeError,
                                UnboundLocalError)):
                raise err  # programming errors must surface, not degrade

    # -- observability --------------------------------------------------

    def snapshot(self) -> dict:
        """gettpuinfo's ``serving`` section."""
        with self._cond:
            out = dict(self.stats)
            out["queue_depth"] = len(self._pending)
            out["inflight_keys"] = len(self._by_key)
            out["inflight_flushes"] = len(self._inflight)
            out["priority_depth"] = self._priority
        out["enabled"] = True
        out["buffers"] = self.buffers
        out["running"] = self.running()
        out["backend"] = self.backend
        # which decompose the GLV flushes ride (ISSUE 11): "device" =
        # the fused in-kernel lattice split, "host" = the numpy-batch
        # fallback, "n/a" = a non-GLV kernel is selected
        from ..ops import ecdsa_batch as _eb

        out["glv_decompose"] = (
            "n/a" if (self.kernel or _eb.active_kernel()) != "glv"
            else ("device" if _eb.glv_dev_enabled() else "host"))
        out["deadline_ms"] = round(self.deadline_s * 1e3, 3)
        out["lanes"] = self.lanes
        out["wait_ms"] = {
            k: round(v * 1e3, 3)
            for k, v in _WAIT_H.quantiles((0.5, 0.9, 0.99)).items()
        }
        out["watchdog"] = dw.WATCHDOG.snapshot().get("sigservice", {})
        return out


# ---------------------------------------------------------------------------
# Tip-relay prewarm: feed a reconstructed/proposed block's non-mempool
# transactions through the service so the imminent connect's sigcache
# probe hits instead of re-verifying inline.
# ---------------------------------------------------------------------------


def prewarm_block_sigs(node, block, timeout: float = 2.0,
                       require_pow: bool = True) -> int:
    """Scan ``block``'s transactions that are NOT in the mempool, defer
    their sigchecks, and settle them through the node's SigService so
    the block connect that follows finds the verdicts in the sigcache.

    Caller holds cs_main. Purely advisory: any scan failure, missing
    input, or service hiccup just skips the transaction — the block
    connect remains the authoritative verdict (an invalid signature is
    simply never inserted into the cache, so nothing can be masked).
    Returns the number of records enqueued.

    Gate order is cost order: the cheap tip-extension/mempool checks
    bail first (IBD never pays anything here), then — P2P callers only
    (``require_pow``) — the header must carry REAL proof of work, and
    the merkle root must commit to the body. Without the PoW gate an
    unsolicited garbage block whose merkle root merely matches its own
    body (free to construct) would buy a full interpreter pass under
    cs_main before the connect rejects it. getblocktemplate proposal
    mode passes require_pow=False: proposals are legitimately unmined,
    and the RPC surface is local/authenticated."""
    svc = getattr(node, "sigservice", None)
    if svc is None or not block.vtx:
        return 0
    cs = node.chainstate
    # tip-relay gate: prewarm pays a second interpreter pass over the
    # non-mempool txs, which only wins when the block is a live tip
    # extension with a populated mempool (during IBD every tx would be
    # scanned twice for nothing)
    if (block.header.hash_prev_block != cs.tip().hash
            or not len(node.mempool.entries)):
        return 0
    if require_pow:
        from ..consensus.pow import check_proof_of_work

        if not check_proof_of_work(block.header.get_hash(),
                                   block.header.bits, cs.params.consensus):
            return 0
    from ..consensus.merkle import block_merkle_root

    root, mutated = block_merkle_root(block)
    if root != block.header.hash_merkle_root or mutated:
        return 0  # body does not match the committed root
    from ..script.interpreter import (
        SCRIPT_VERIFY_NULLFAIL,
        DeferringSignatureChecker,
        ScriptError,
        VerifyScript,
    )
    from ..script.sighash import SighashCache
    from ..validation.scriptcheck import block_script_flags

    prev = cs.block_index.get(block.header.hash_prev_block)
    height = (prev.height + 1) if prev is not None else cs.tip().height + 1
    flags = block_script_flags(height, block.header.time, cs.params)
    if not flags & SCRIPT_VERIFY_NULLFAIL:
        return 0  # pre-NULLFAIL era: deferral unsound
    in_block: dict[bytes, object] = {tx.txid: tx for tx in block.vtx}
    records: list = []
    n_txs = 0
    for tx in block.vtx[1:]:
        if tx.txid in node.mempool.entries:
            continue  # verified at accept; sigcache already has it
        tx_records: list = []
        cache = SighashCache(tx)
        try:
            for i, txin in enumerate(tx.vin):
                parent = in_block.get(txin.prevout.hash)
                if parent is not None:
                    out = parent.vout[txin.prevout.n]
                    value, spk = out.value, out.script_pubkey
                else:
                    coin = cs.coins.get_coin(txin.prevout)
                    if coin is None:
                        out = node.mempool.get_output(txin.prevout)
                        if out is None:
                            raise LookupError("missing input")
                        value, spk = out.value, out.script_pubkey
                    else:
                        value, spk = coin.out.value, coin.out.script_pubkey
                checker = DeferringSignatureChecker(
                    tx, i, value, tx_records, cache)
                VerifyScript(txin.script_sig, spk, flags, checker)
        except (KeyboardInterrupt, SystemExit):
            raise
        except (ScriptError, LookupError, IndexError, ValueError):
            continue  # connect gives the authoritative verdict
        records.extend(tx_records)
        n_txs += 1
    if not records:
        return 0
    with svc._cond:
        svc.stats["prewarm_txs"] += n_txs
        svc.stats["prewarm_records"] += len(records)
    try:
        # advisory wait, NOT result(): a backlogged service must cost the
        # relay path at most ``timeout`` — late settles still warm the
        # sigcache, and the connect re-verifies whatever missed it
        svc.submit(records).wait(timeout)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:  # noqa: BLE001 — advisory path
        pass
    return len(records)
