"""ctypes bindings for the native runtime library (native/bcp_native.cpp).

The reference's runtime around the compute path is C++ (serialization
templates, src/crypto/sha256.cpp, merkle.cpp); here the equivalent native
layer accelerates the HOST side of -reindex / block-store scans: wire
parsing (tx boundaries + txids), batch header hashing, merkle roots. The
TPU kernels remain the device compute path; Python remains the consensus
reference — callers treat this as an optional accelerator and every
function is differential-tested against the Python implementation
(tests/unit/test_native.py).

`load()` finds (or builds, if a toolchain is present) native/libbcpnative.so
and returns None when unavailable — callers must keep the Python path.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libbcpnative.so")

_lib = None
_load_attempted = False


def load() -> Optional[ctypes.CDLL]:
    """dlopen the native library, (re)building it first when a toolchain is
    present. Returns None (and remembers) when unavailable.

    The build always runs `make` (its dependency tracking makes a fresh
    .so a no-op, and skipping it would silently keep loading a stale binary
    after bcp_native.cpp edits) under an flock — concurrent bcpd processes
    on a fresh checkout must not race the compiler or dlopen a half-written
    file (g++ writes -o in place)."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("BCP_NO_NATIVE"):
        return None
    if os.path.isdir(_NATIVE_DIR) and os.access(_NATIVE_DIR, os.W_OK):
        try:
            import fcntl

            with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                subprocess.run(["make", "-C", _NATIVE_DIR],
                               capture_output=True, timeout=120, check=True)
        except Exception:
            if not os.path.exists(_LIB_PATH):
                return None  # no toolchain and no prebuilt library
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        _bind(lib)
    except (OSError, AttributeError):
        # missing library, or a stale prebuilt .so lacking newer symbols
        # (build skipped/failed): honor the "None when unavailable"
        # contract — callers keep the Python path
        return None
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.bcp_engine_new.argtypes = []
    lib.bcp_engine_new.restype = ctypes.c_void_p
    lib.bcp_engine_free.argtypes = [ctypes.c_void_p]
    lib.bcp_engine_free.restype = None
    lib.bcp_engine_set_best.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bcp_engine_set_best.restype = None
    lib.bcp_engine_get_best.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bcp_engine_get_best.restype = None
    lib.bcp_engine_mem_bytes.argtypes = [ctypes.c_void_p]
    lib.bcp_engine_mem_bytes.restype = ctypes.c_uint64
    lib.bcp_engine_entries.argtypes = [ctypes.c_void_p]
    lib.bcp_engine_entries.restype = ctypes.c_long
    lib.bcp_engine_insert.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.bcp_engine_insert.restype = None
    lib.bcp_engine_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(u8p),
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.bcp_engine_get.restype = ctypes.c_int
    lib.bcp_engine_error.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long),
    ]
    lib.bcp_engine_error.restype = ctypes.c_long
    lib.bcp_engine_missing.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)
    ]
    lib.bcp_engine_missing.restype = u8p
    lib.bcp_engine_undo.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t)
    ]
    lib.bcp_engine_undo.restype = u8p
    for name in ("bcp_engine_n_tx", "bcp_engine_n_inputs"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = ctypes.c_long
    for name, rt in (
        ("bcp_engine_txids", u8p),
        ("bcp_engine_tx_offsets", ctypes.POINTER(ctypes.c_uint64)),
        ("bcp_engine_tx_out_counts", ctypes.POINTER(ctypes.c_uint32)),
        ("bcp_engine_spent_values", ctypes.POINTER(ctypes.c_int64)),
        ("bcp_engine_spent_heightcodes", ctypes.POINTER(ctypes.c_uint32)),
        ("bcp_engine_spent_spk_offsets", ctypes.POINTER(ctypes.c_uint32)),
        ("bcp_engine_sig_status", u8p),
        ("bcp_engine_sig_msg", u8p),
        ("bcp_engine_sig_rs", u8p),
        ("bcp_engine_sig_pub", u8p),
        ("bcp_engine_sig_rn", u8p),
        ("bcp_engine_sig_wrap", u8p),
        ("bcp_engine_sig_txin", ctypes.POINTER(ctypes.c_uint32)),
    ):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = rt
    lib.bcp_engine_spent_spk_blob.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t)
    ]
    lib.bcp_engine_spent_spk_blob.restype = u8p
    lib.bcp_engine_connect_block.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_uint32, ctypes.c_int64, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p,
    ]
    lib.bcp_engine_connect_block.restype = ctypes.c_long
    lib.bcp_engine_commit.argtypes = [ctypes.c_void_p]
    lib.bcp_engine_commit.restype = None
    lib.bcp_engine_sigscan_ns.argtypes = [ctypes.c_void_p]
    lib.bcp_engine_sigscan_ns.restype = ctypes.c_uint64
    lib.bcp_engine_abort.argtypes = [ctypes.c_void_p]
    lib.bcp_engine_abort.restype = None
    lib.bcp_engine_flush.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_long),
    ]
    lib.bcp_engine_flush.restype = u8p
    lib.bcp_engine_clear.argtypes = [ctypes.c_void_p]
    lib.bcp_engine_clear.restype = None
    lib.bcp_sha256d.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                ctypes.c_char_p]
    lib.bcp_sha256d.restype = None
    lib.bcp_hash_headers.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                     ctypes.c_char_p]
    lib.bcp_hash_headers.restype = None
    lib.bcp_scan_block.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                   ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_uint64),
                                   ctypes.c_long]
    lib.bcp_scan_block.restype = ctypes.c_long
    lib.bcp_merkle_root.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                    ctypes.c_char_p]
    lib.bcp_merkle_root.restype = ctypes.c_long
    lib.bcp_ecdsa_verify.argtypes = [ctypes.c_char_p] * 3
    lib.bcp_ecdsa_verify.restype = ctypes.c_int
    lib.bcp_ecdsa_verify_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.bcp_ecdsa_verify_batch.restype = None
    lib.bcp_ecdsa_precompute.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.bcp_ecdsa_precompute.restype = None
    lib.bcp_ecdsa_sign.argtypes = [ctypes.c_char_p] * 4
    lib.bcp_ecdsa_sign.restype = ctypes.c_int
    lib.bcp_pubkey_parse.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                     ctypes.c_char_p]
    lib.bcp_pubkey_parse.restype = ctypes.c_int


def available() -> bool:
    return load() is not None


def sha256d(data: bytes) -> bytes:
    lib = load()
    assert lib is not None, "native library unavailable"
    out = ctypes.create_string_buffer(32)
    lib.bcp_sha256d(data, len(data), out)
    return out.raw


def hash_headers(headers: bytes) -> list[bytes]:
    """n concatenated 80-byte headers -> n sha256d digests."""
    assert len(headers) % 80 == 0
    n = len(headers) // 80
    lib = load()
    assert lib is not None, "native library unavailable"
    out = ctypes.create_string_buffer(32 * n)
    lib.bcp_hash_headers(headers, n, out)
    raw = out.raw  # ONE copy: .raw copies the whole buffer per access
    return [raw[32 * i:32 * i + 32] for i in range(n)]


class BlockScan:
    __slots__ = ("txids", "offsets")

    def __init__(self, txids: list[bytes], offsets: list[tuple[int, int]]):
        self.txids = txids
        self.offsets = offsets


def scan_block(raw: bytes, max_tx: int = 100_000) -> Optional[BlockScan]:
    """Wire-scan a serialized block: per-tx txids + [start, end) offsets.
    None on truncated/corrupt input (callers fall back to the Python
    deserializer, which raises the detailed error)."""
    lib = load()
    assert lib is not None, "native library unavailable"
    # a serialized tx is >= ~10 bytes: size the buffers by the input, not
    # the worst case (txindex backfill calls this once per block)
    max_tx = min(max_tx, len(raw) // 10 + 1)
    txids = ctypes.create_string_buffer(32 * max_tx)
    offsets = (ctypes.c_uint64 * (2 * max_tx))()
    n = lib.bcp_scan_block(raw, len(raw), txids, offsets, max_tx)
    if n < 0:
        return None
    raw_txids = txids.raw  # ONE copy (see hash_headers)
    return BlockScan(
        [raw_txids[32 * i:32 * i + 32] for i in range(n)],
        [(int(offsets[2 * i]), int(offsets[2 * i + 1])) for i in range(n)],
    )


# Thread budget for batch entry points. 0 = one thread per core (the C++
# side resolves it); node init assigns this from -par (node/node.py).
PAR_THREADS = 0


def _pack_rs_msg(records) -> tuple[bytes, bytes]:
    """(r||s, msg_hash) blobs for the batch entry points (32-byte
    big-endian fields, mod 2^256 — the C side range-rejects r/s >= n)."""
    rs = b"".join(
        (rec.r % (1 << 256)).to_bytes(32, "big")
        + (rec.s % (1 << 256)).to_bytes(32, "big")
        for rec in records
    )
    msg = b"".join(
        (rec.msg_hash % (1 << 256)).to_bytes(32, "big") for rec in records
    )
    return rs, msg


def ecdsa_verify(pubkey: tuple, r: int, s: int, e: int) -> bool:
    """Scalar ECDSA verify on the native module (same acceptance set as
    crypto/secp256k1.ecdsa_verify — differentially tested). The pubkey is
    an affine (x, y) pair as produced by pubkey_parse."""
    lib = load()
    assert lib is not None, "native library unavailable"
    pub = pubkey[0].to_bytes(32, "big") + pubkey[1].to_bytes(32, "big")
    rs = (r % (1 << 256)).to_bytes(32, "big") + \
        (s % (1 << 256)).to_bytes(32, "big")
    msg = (e % (1 << 256)).to_bytes(32, "big")
    return bool(lib.bcp_ecdsa_verify(pub, rs, msg))


def ecdsa_verify_batch(records, nthreads: int | None = None) -> list[bool]:
    """Batch verify SigCheckRecord-shaped objects (.pubkey/.r/.s/.msg_hash)
    across host threads — the CPU fallback lane of ops/ecdsa_batch."""
    lib = load()
    assert lib is not None, "native library unavailable"
    n = len(records)
    if n == 0:
        return []
    pub = b"".join(
        rec.pubkey[0].to_bytes(32, "big") + rec.pubkey[1].to_bytes(32, "big")
        for rec in records
    )
    rs, msg = _pack_rs_msg(records)
    ok = ctypes.create_string_buffer(n)
    lib.bcp_ecdsa_verify_batch(pub, rs, msg, n, ok,
                               nthreads if nthreads is not None
                               else PAR_THREADS)
    return [b == 1 for b in ok.raw]


def ecdsa_precompute(records, nthreads: int | None = None):
    """Per-record u1 = e*s^-1 mod n, u2 = r*s^-1 mod n as two n*32-byte
    big-endian blobs (+ per-record validity flags) — the host scalar leg of
    the TPU batch packer, replacing the Python-int pow() loop."""
    lib = load()
    assert lib is not None, "native library unavailable"
    n = len(records)
    if n == 0:
        return b"", b"", []
    rs, msg = _pack_rs_msg(records)
    u1 = ctypes.create_string_buffer(32 * n)
    u2 = ctypes.create_string_buffer(32 * n)
    ok = ctypes.create_string_buffer(n)
    lib.bcp_ecdsa_precompute(rs, msg, n, u1, u2, ok,
                             nthreads if nthreads is not None
                             else PAR_THREADS)
    return u1.raw, u2.raw, [b == 1 for b in ok.raw]


def pubkey_parse(data: bytes):
    """CPubKey parse/decompress (same acceptance as the oracle's
    pubkey_parse — compressed sqrt, uncompressed/hybrid on-curve checks).
    Returns affine (x, y) or None. ~30x the Python path for compressed
    keys (the modular sqrt dominates)."""
    lib = load()
    assert lib is not None, "native library unavailable"
    out = ctypes.create_string_buffer(64)
    if not lib.bcp_pubkey_parse(data, len(data), out):
        return None
    return (int.from_bytes(out.raw[:32], "big"),
            int.from_bytes(out.raw[32:], "big"))


def ecdsa_sign(secret: int, e: int) -> tuple[int, int]:
    """RFC6979-deterministic ECDSA sign, bit-identical to the oracle signer
    (crypto/secp256k1.ecdsa_sign): the nonce derivation runs in Python
    (HMAC — microseconds), the EC math runs native (~100x the Python-int
    point_mul). Low-s normalized."""
    from .crypto.secp256k1 import N, rfc6979_nonce

    lib = load()
    assert lib is not None, "native library unavailable"
    sk = secret.to_bytes(32, "big")
    eb = (e % (1 << 256)).to_bytes(32, "big")
    out = ctypes.create_string_buffer(64)
    k = rfc6979_nonce(secret, e)
    extra = 0
    while not lib.bcp_ecdsa_sign(sk, eb, k.to_bytes(32, "big"), out):
        # r == 0 / s == 0 (cryptographically unreachable): next candidate
        # nonce, same retry semantics as the oracle's while-loop
        extra += 1
        k = rfc6979_nonce(secret, e, extra.to_bytes(4, "big"))
        assert 1 <= k < N
    return (int.from_bytes(out.raw[:32], "big"),
            int.from_bytes(out.raw[32:], "big"))


def merkle_root(txids: list[bytes]) -> tuple[bytes, bool]:
    """(root, mutated) — ComputeMerkleRoot with the CVE-2012-2459 flag."""
    lib = load()
    assert lib is not None, "native library unavailable"
    n = len(txids)
    if n == 0:
        return b"\x00" * 32, False
    buf = b"".join(txids)
    out = ctypes.create_string_buffer(32)
    mutated = lib.bcp_merkle_root(buf, n, out)
    return out.raw, bool(mutated)


# ---------------------------------------------------------------------------
# Block-connect engine (native/connect.cpp) — the C++ ConnectBlock hot path
# for -reindex. Reference: src/validation.cpp LoadExternalBlockFile/
# ConnectBlock, src/coins.cpp. Semantics mirror validation/chainstate.py;
# differential tests: tests/unit/test_native_connect.py.
# ---------------------------------------------------------------------------

# engine error code -> (reject reason, is_script_error) matching the Python
# path's BlockValidationError reasons / ScriptError codes
ENGINE_ERRORS = {
    -1: "deserialize",
    -2: "bad-txnmrklroot",
    -3: "bad-txns-duplicate",
    -4: "bad-blk-length",
    -5: "bad-blk-length",
    -6: "bad-cb-missing",
    -7: "bad-cb-multiple",
    -8: "bad-txns-vin-empty",
    -9: "bad-txns-vout-empty",
    -10: "bad-txns-oversize",
    -11: "bad-txns-vout-negative",
    -12: "bad-txns-vout-toolarge",
    -13: "bad-txns-txouttotal-toolarge",
    -14: "bad-txns-inputs-duplicate",
    -15: "bad-cb-length",
    -16: "bad-txns-prevout-null",
    -17: "bad-txns-nonfinal",
    -18: "bad-cb-height",
    -19: "bad-txns-BIP30",
    -20: "bad-txns-inputs-missingorspent",
    -21: "bad-txns-premature-spend-of-coinbase",
    -22: "bad-txns-inputvalues-outofrange",
    -23: "bad-txns-in-belowout",
    -24: "bad-txns-fee-outofrange",
    -25: "bad-cb-amount",
    # script errors (block-fatal, ScriptError codes)
    -101: "equalverify",
    -102: "sig-der",
    -103: "sig-high-s",
    -104: "sig-hashtype",
    -105: "illegal-forkid",
    -106: "must-use-forkid",
    -107: "pubkeytype",
    -108: "sig-nullfail",
    -109: "eval-false",
}


class NativeConnectResult:
    """Successful native connect: everything the Python orchestration layer
    needs, copied out of the engine's scratch buffers (which the next engine
    call reuses). Sig arrays are numpy for vectorized compaction."""

    __slots__ = ("block_hash", "n_tx", "n_inputs", "undo", "txids_blob",
                 "sigscan_s",
                 "tx_offsets", "tx_out_counts", "sig_status", "sig_msg",
                 "sig_rs", "sig_pub", "sig_rn", "sig_wrap", "sig_txin",
                 "spent_values", "spent_heightcodes", "spent_spk_offsets",
                 "spent_spk_blob")

    def txid(self, i: int) -> bytes:
        return self.txids_blob[32 * i:32 * i + 32]

    def txids(self) -> list[bytes]:
        blob = self.txids_blob
        return [blob[32 * i:32 * i + 32] for i in range(self.n_tx)]


class EngineMissing(Exception):
    """Connect needs prevouts not in the engine map; .keys are the 36-byte
    outpoint keys to fetch from the base store and insert."""

    def __init__(self, keys: list[bytes]):
        super().__init__(f"{len(keys)} prevouts not cached")
        self.keys = keys


class EngineError(Exception):
    """Native validation verdict (advisory: the import path re-runs the
    block through the Python engine for the authoritative error)."""

    def __init__(self, reason: str, tx_idx: int, in_idx: int,
                 is_script: bool):
        super().__init__(f"{reason} (tx {tx_idx} input {in_idx})")
        self.reason = reason
        self.tx_idx = tx_idx
        self.in_idx = in_idx
        self.is_script = is_script


def _np():
    import numpy

    return numpy


class ConnectEngine:
    """The in-memory UTXO cache + block-connect engine (CCoinsViewCache +
    ConnectBlock in C++). One instance per import session; NOT thread-safe
    (the import loop is single-threaded; the engine threads internally)."""

    def __init__(self):
        lib = load()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self._h = lib.bcp_engine_new()

    def close(self):
        if self._h:
            self._lib.bcp_engine_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass

    # -- coin cache ----------------------------------------------------

    def insert(self, key36: bytes, height_code: int, value: int,
               spk: bytes) -> None:
        self._lib.bcp_engine_insert(self._h, key36, height_code, value,
                                    spk, len(spk))

    def get(self, key36: bytes):
        """(height_code, value, spk) for a live coin; None if absent;
        the string "spent" for a tombstone."""
        hc = ctypes.c_uint32()
        val = ctypes.c_int64()
        spk = ctypes.POINTER(ctypes.c_uint8)()
        spk_len = ctypes.c_uint32()
        rc = self._lib.bcp_engine_get(
            self._h, key36, ctypes.byref(hc), ctypes.byref(val),
            ctypes.byref(spk), ctypes.byref(spk_len))
        if rc == 0:
            return None
        if rc == -1:
            return "spent"
        return (hc.value, val.value,
                ctypes.string_at(spk, spk_len.value))

    def mem_bytes(self) -> int:
        return self._lib.bcp_engine_mem_bytes(self._h)

    def entries(self) -> int:
        return self._lib.bcp_engine_entries(self._h)

    def set_best(self, h32: bytes) -> None:
        self._lib.bcp_engine_set_best(self._h, h32)

    def best(self) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.bcp_engine_get_best(self._h, out)
        return out.raw

    # -- connect -------------------------------------------------------

    def connect_block(self, raw: bytes, height: int, subsidy: int,
                      max_block_size: int, coinbase_maturity: int,
                      mtp: int, bip34_prefix: bytes | None,
                      script_flags: int, want_sigs: bool,
                      check_merkle: bool = True, nthreads: int = 0,
                      commit: bool = True) -> NativeConnectResult:
        """Validate + (optionally) apply one block. commit=False stages the
        UTXO edits; call commit()/abort() after the caller's own script
        checks settle — the Python fallback interpreter runs in between."""
        lib = self._lib
        hash_out = ctypes.create_string_buffer(32)
        rc = lib.bcp_engine_connect_block(
            self._h, raw, len(raw), height, subsidy, max_block_size,
            coinbase_maturity, mtp,
            bip34_prefix if bip34_prefix else None,
            len(bip34_prefix) if bip34_prefix else 0,
            script_flags, 1 if want_sigs else 0,
            1 if check_merkle else 0, nthreads,
            1 if commit else 0, hash_out)
        if rc == 1:
            n = ctypes.c_long()
            ptr = lib.bcp_engine_missing(self._h, ctypes.byref(n))
            blob = ctypes.string_at(ptr, 36 * n.value)
            raise EngineMissing(
                [blob[36 * i:36 * i + 36] for i in range(n.value)])
        if rc < 0:
            t = ctypes.c_long()
            i = ctypes.c_long()
            code = lib.bcp_engine_error(self._h, ctypes.byref(t),
                                        ctypes.byref(i))
            raise EngineError(ENGINE_ERRORS.get(code, f"native-{code}"),
                              t.value, i.value, code <= -100)
        np = _np()
        res = NativeConnectResult()
        res.block_hash = hash_out.raw
        res.sigscan_s = lib.bcp_engine_sigscan_ns(self._h) / 1e9
        res.n_tx = lib.bcp_engine_n_tx(self._h)
        res.n_inputs = lib.bcp_engine_n_inputs(self._h)
        ulen = ctypes.c_size_t()
        uptr = lib.bcp_engine_undo(self._h, ctypes.byref(ulen))
        res.undo = ctypes.string_at(uptr, ulen.value)
        res.txids_blob = ctypes.string_at(lib.bcp_engine_txids(self._h),
                                          32 * res.n_tx)
        res.tx_offsets = np.frombuffer(
            ctypes.string_at(lib.bcp_engine_tx_offsets(self._h),
                             16 * res.n_tx), np.uint64).reshape(res.n_tx, 2)
        res.tx_out_counts = np.frombuffer(
            ctypes.string_at(lib.bcp_engine_tx_out_counts(self._h),
                             4 * res.n_tx), np.uint32)
        n = res.n_inputs
        if n:
            res.sig_status = np.frombuffer(
                ctypes.string_at(lib.bcp_engine_sig_status(self._h), n),
                np.uint8)
            res.sig_txin = np.frombuffer(
                ctypes.string_at(lib.bcp_engine_sig_txin(self._h), 8 * n),
                np.uint32).reshape(n, 2)
            if want_sigs:
                res.sig_msg = np.frombuffer(
                    ctypes.string_at(lib.bcp_engine_sig_msg(self._h),
                                     32 * n), np.uint8).reshape(n, 32)
                res.sig_rs = np.frombuffer(
                    ctypes.string_at(lib.bcp_engine_sig_rs(self._h),
                                     64 * n), np.uint8).reshape(n, 64)
                res.sig_pub = np.frombuffer(
                    ctypes.string_at(lib.bcp_engine_sig_pub(self._h),
                                     64 * n), np.uint8).reshape(n, 64)
                res.sig_rn = np.frombuffer(
                    ctypes.string_at(lib.bcp_engine_sig_rn(self._h),
                                     32 * n), np.uint8).reshape(n, 32)
                res.sig_wrap = np.frombuffer(
                    ctypes.string_at(lib.bcp_engine_sig_wrap(self._h), n),
                    np.uint8)
            res.spent_values = np.frombuffer(
                ctypes.string_at(lib.bcp_engine_spent_values(self._h),
                                 8 * n), np.int64)
            res.spent_heightcodes = np.frombuffer(
                ctypes.string_at(lib.bcp_engine_spent_heightcodes(self._h),
                                 4 * n), np.uint32)
            res.spent_spk_offsets = np.frombuffer(
                ctypes.string_at(lib.bcp_engine_spent_spk_offsets(self._h),
                                 4 * (n + 1)), np.uint32)
            slen = ctypes.c_size_t()
            sptr = lib.bcp_engine_spent_spk_blob(self._h,
                                                 ctypes.byref(slen))
            res.spent_spk_blob = ctypes.string_at(sptr, slen.value)
        return res

    def commit(self) -> None:
        """Apply a connect_block(commit=False) staging."""
        self._lib.bcp_engine_commit(self._h)

    def abort(self) -> None:
        """Discard a connect_block(commit=False) staging."""
        self._lib.bcp_engine_abort(self._h)

    # -- flush ---------------------------------------------------------

    def flush_entries(self):
        """Yield (key36, coin_serialization | None-for-delete) for every
        dirty entry; the caller writes the CoinsDB batch then calls
        clear(). Entry format documented at bcp_engine_flush."""
        ln = ctypes.c_size_t()
        n = ctypes.c_long()
        ptr = self._lib.bcp_engine_flush(self._h, ctypes.byref(ln),
                                         ctypes.byref(n))
        blob = ctypes.string_at(ptr, ln.value)
        out = []
        pos = 0
        for _ in range(n.value):
            key = blob[pos:pos + 36]
            tag = blob[pos + 36]
            pos += 37
            if tag == 0:
                out.append((key, None))
            else:
                (clen,) = struct.unpack_from("<I", blob, pos)
                pos += 4
                out.append((key, blob[pos:pos + clen]))
                pos += clen
        return out

    def clear(self) -> None:
        self._lib.bcp_engine_clear(self._h)


def engine_available() -> bool:
    """True when the connect engine's symbols are present (a stale prebuilt
    .so without them makes load() return None already)."""
    lib = load()
    return lib is not None and hasattr(lib, "bcp_engine_new")


# -- blob-level ECDSA batch entries (the native sigscan's outputs feed these
# directly — no per-record Python int round trip) ---------------------------

def ecdsa_precompute_blobs(rs: bytes, msg: bytes, n: int,
                           nthreads: int | None = None):
    """u1/u2 blobs + validity flags from raw (r||s, msg) blobs — the blob
    form of ecdsa_precompute (same C entry point)."""
    lib = load()
    assert lib is not None, "native library unavailable"
    if n == 0:
        return b"", b"", []
    u1 = ctypes.create_string_buffer(32 * n)
    u2 = ctypes.create_string_buffer(32 * n)
    ok = ctypes.create_string_buffer(n)
    lib.bcp_ecdsa_precompute(rs, msg, n, u1, u2, ok,
                             nthreads if nthreads is not None
                             else PAR_THREADS)
    return u1.raw, u2.raw, [b == 1 for b in ok.raw]


def ecdsa_verify_batch_blobs(pub: bytes, rs: bytes, msg: bytes, n: int,
                             nthreads: int | None = None) -> list[bool]:
    """Blob form of ecdsa_verify_batch (threaded native scalar verify)."""
    lib = load()
    assert lib is not None, "native library unavailable"
    if n == 0:
        return []
    ok = ctypes.create_string_buffer(n)
    lib.bcp_ecdsa_verify_batch(pub, rs, msg, n, ok,
                               nthreads if nthreads is not None
                               else PAR_THREADS)
    return [b == 1 for b in ok.raw]
