"""ctypes bindings for the native runtime library (native/bcp_native.cpp).

The reference's runtime around the compute path is C++ (serialization
templates, src/crypto/sha256.cpp, merkle.cpp); here the equivalent native
layer accelerates the HOST side of -reindex / block-store scans: wire
parsing (tx boundaries + txids), batch header hashing, merkle roots. The
TPU kernels remain the device compute path; Python remains the consensus
reference — callers treat this as an optional accelerator and every
function is differential-tested against the Python implementation
(tests/unit/test_native.py).

`load()` finds (or builds, if a toolchain is present) native/libbcpnative.so
and returns None when unavailable — callers must keep the Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libbcpnative.so")

_lib = None
_load_attempted = False


def load() -> Optional[ctypes.CDLL]:
    """dlopen the native library, (re)building it first when a toolchain is
    present. Returns None (and remembers) when unavailable.

    The build always runs `make` (its dependency tracking makes a fresh
    .so a no-op, and skipping it would silently keep loading a stale binary
    after bcp_native.cpp edits) under an flock — concurrent bcpd processes
    on a fresh checkout must not race the compiler or dlopen a half-written
    file (g++ writes -o in place)."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("BCP_NO_NATIVE"):
        return None
    if os.path.isdir(_NATIVE_DIR) and os.access(_NATIVE_DIR, os.W_OK):
        try:
            import fcntl

            with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                subprocess.run(["make", "-C", _NATIVE_DIR],
                               capture_output=True, timeout=120, check=True)
        except Exception:
            if not os.path.exists(_LIB_PATH):
                return None  # no toolchain and no prebuilt library
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        _bind(lib)
    except (OSError, AttributeError):
        # missing library, or a stale prebuilt .so lacking newer symbols
        # (build skipped/failed): honor the "None when unavailable"
        # contract — callers keep the Python path
        return None
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.bcp_sha256d.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                ctypes.c_char_p]
    lib.bcp_sha256d.restype = None
    lib.bcp_hash_headers.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                     ctypes.c_char_p]
    lib.bcp_hash_headers.restype = None
    lib.bcp_scan_block.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                   ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_uint64),
                                   ctypes.c_long]
    lib.bcp_scan_block.restype = ctypes.c_long
    lib.bcp_merkle_root.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                    ctypes.c_char_p]
    lib.bcp_merkle_root.restype = ctypes.c_long
    lib.bcp_ecdsa_verify.argtypes = [ctypes.c_char_p] * 3
    lib.bcp_ecdsa_verify.restype = ctypes.c_int
    lib.bcp_ecdsa_verify_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.bcp_ecdsa_verify_batch.restype = None
    lib.bcp_ecdsa_precompute.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.bcp_ecdsa_precompute.restype = None
    lib.bcp_ecdsa_sign.argtypes = [ctypes.c_char_p] * 4
    lib.bcp_ecdsa_sign.restype = ctypes.c_int
    lib.bcp_pubkey_parse.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                     ctypes.c_char_p]
    lib.bcp_pubkey_parse.restype = ctypes.c_int


def available() -> bool:
    return load() is not None


def sha256d(data: bytes) -> bytes:
    lib = load()
    assert lib is not None, "native library unavailable"
    out = ctypes.create_string_buffer(32)
    lib.bcp_sha256d(data, len(data), out)
    return out.raw


def hash_headers(headers: bytes) -> list[bytes]:
    """n concatenated 80-byte headers -> n sha256d digests."""
    assert len(headers) % 80 == 0
    n = len(headers) // 80
    lib = load()
    assert lib is not None, "native library unavailable"
    out = ctypes.create_string_buffer(32 * n)
    lib.bcp_hash_headers(headers, n, out)
    raw = out.raw  # ONE copy: .raw copies the whole buffer per access
    return [raw[32 * i:32 * i + 32] for i in range(n)]


class BlockScan:
    __slots__ = ("txids", "offsets")

    def __init__(self, txids: list[bytes], offsets: list[tuple[int, int]]):
        self.txids = txids
        self.offsets = offsets


def scan_block(raw: bytes, max_tx: int = 100_000) -> Optional[BlockScan]:
    """Wire-scan a serialized block: per-tx txids + [start, end) offsets.
    None on truncated/corrupt input (callers fall back to the Python
    deserializer, which raises the detailed error)."""
    lib = load()
    assert lib is not None, "native library unavailable"
    # a serialized tx is >= ~10 bytes: size the buffers by the input, not
    # the worst case (txindex backfill calls this once per block)
    max_tx = min(max_tx, len(raw) // 10 + 1)
    txids = ctypes.create_string_buffer(32 * max_tx)
    offsets = (ctypes.c_uint64 * (2 * max_tx))()
    n = lib.bcp_scan_block(raw, len(raw), txids, offsets, max_tx)
    if n < 0:
        return None
    raw_txids = txids.raw  # ONE copy (see hash_headers)
    return BlockScan(
        [raw_txids[32 * i:32 * i + 32] for i in range(n)],
        [(int(offsets[2 * i]), int(offsets[2 * i + 1])) for i in range(n)],
    )


# Thread budget for batch entry points. 0 = one thread per core (the C++
# side resolves it); node init assigns this from -par (node/node.py).
PAR_THREADS = 0


def _pack_rs_msg(records) -> tuple[bytes, bytes]:
    """(r||s, msg_hash) blobs for the batch entry points (32-byte
    big-endian fields, mod 2^256 — the C side range-rejects r/s >= n)."""
    rs = b"".join(
        (rec.r % (1 << 256)).to_bytes(32, "big")
        + (rec.s % (1 << 256)).to_bytes(32, "big")
        for rec in records
    )
    msg = b"".join(
        (rec.msg_hash % (1 << 256)).to_bytes(32, "big") for rec in records
    )
    return rs, msg


def ecdsa_verify(pubkey: tuple, r: int, s: int, e: int) -> bool:
    """Scalar ECDSA verify on the native module (same acceptance set as
    crypto/secp256k1.ecdsa_verify — differentially tested). The pubkey is
    an affine (x, y) pair as produced by pubkey_parse."""
    lib = load()
    assert lib is not None, "native library unavailable"
    pub = pubkey[0].to_bytes(32, "big") + pubkey[1].to_bytes(32, "big")
    rs = (r % (1 << 256)).to_bytes(32, "big") + \
        (s % (1 << 256)).to_bytes(32, "big")
    msg = (e % (1 << 256)).to_bytes(32, "big")
    return bool(lib.bcp_ecdsa_verify(pub, rs, msg))


def ecdsa_verify_batch(records, nthreads: int | None = None) -> list[bool]:
    """Batch verify SigCheckRecord-shaped objects (.pubkey/.r/.s/.msg_hash)
    across host threads — the CPU fallback lane of ops/ecdsa_batch."""
    lib = load()
    assert lib is not None, "native library unavailable"
    n = len(records)
    if n == 0:
        return []
    pub = b"".join(
        rec.pubkey[0].to_bytes(32, "big") + rec.pubkey[1].to_bytes(32, "big")
        for rec in records
    )
    rs, msg = _pack_rs_msg(records)
    ok = ctypes.create_string_buffer(n)
    lib.bcp_ecdsa_verify_batch(pub, rs, msg, n, ok,
                               nthreads if nthreads is not None
                               else PAR_THREADS)
    return [b == 1 for b in ok.raw]


def ecdsa_precompute(records, nthreads: int | None = None):
    """Per-record u1 = e*s^-1 mod n, u2 = r*s^-1 mod n as two n*32-byte
    big-endian blobs (+ per-record validity flags) — the host scalar leg of
    the TPU batch packer, replacing the Python-int pow() loop."""
    lib = load()
    assert lib is not None, "native library unavailable"
    n = len(records)
    if n == 0:
        return b"", b"", []
    rs, msg = _pack_rs_msg(records)
    u1 = ctypes.create_string_buffer(32 * n)
    u2 = ctypes.create_string_buffer(32 * n)
    ok = ctypes.create_string_buffer(n)
    lib.bcp_ecdsa_precompute(rs, msg, n, u1, u2, ok,
                             nthreads if nthreads is not None
                             else PAR_THREADS)
    return u1.raw, u2.raw, [b == 1 for b in ok.raw]


def pubkey_parse(data: bytes):
    """CPubKey parse/decompress (same acceptance as the oracle's
    pubkey_parse — compressed sqrt, uncompressed/hybrid on-curve checks).
    Returns affine (x, y) or None. ~30x the Python path for compressed
    keys (the modular sqrt dominates)."""
    lib = load()
    assert lib is not None, "native library unavailable"
    out = ctypes.create_string_buffer(64)
    if not lib.bcp_pubkey_parse(data, len(data), out):
        return None
    return (int.from_bytes(out.raw[:32], "big"),
            int.from_bytes(out.raw[32:], "big"))


def ecdsa_sign(secret: int, e: int) -> tuple[int, int]:
    """RFC6979-deterministic ECDSA sign, bit-identical to the oracle signer
    (crypto/secp256k1.ecdsa_sign): the nonce derivation runs in Python
    (HMAC — microseconds), the EC math runs native (~100x the Python-int
    point_mul). Low-s normalized."""
    from .crypto.secp256k1 import N, rfc6979_nonce

    lib = load()
    assert lib is not None, "native library unavailable"
    sk = secret.to_bytes(32, "big")
    eb = (e % (1 << 256)).to_bytes(32, "big")
    out = ctypes.create_string_buffer(64)
    k = rfc6979_nonce(secret, e)
    extra = 0
    while not lib.bcp_ecdsa_sign(sk, eb, k.to_bytes(32, "big"), out):
        # r == 0 / s == 0 (cryptographically unreachable): next candidate
        # nonce, same retry semantics as the oracle's while-loop
        extra += 1
        k = rfc6979_nonce(secret, e, extra.to_bytes(4, "big"))
        assert 1 <= k < N
    return (int.from_bytes(out.raw[:32], "big"),
            int.from_bytes(out.raw[32:], "big"))


def merkle_root(txids: list[bytes]) -> tuple[bytes, bool]:
    """(root, mutated) — ComputeMerkleRoot with the CVE-2012-2459 flag."""
    lib = load()
    assert lib is not None, "native library unavailable"
    n = len(txids)
    if n == 0:
        return b"\x00" * 32, False
    buf = b"".join(txids)
    out = ctypes.create_string_buffer(32)
    mutated = lib.bcp_merkle_root(buf, n, out)
    return out.raw, bool(mutated)
