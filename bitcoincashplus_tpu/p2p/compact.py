"""BIP152 compact block relay structures.

Reference: src/blockencodings.{h,cpp} (CBlockHeaderAndShortTxIDs,
BlockTransactionsRequest, BlockTransactions, PartiallyDownloadedBlock),
protocol version 1 (no segwit in this lineage). Short IDs are
SipHash-2-4(txid) under a per-block key derived from the header+nonce,
truncated to 48 bits.
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Optional

from ..consensus.block import CBlock, CBlockHeader
from ..consensus.serialize import (
    ByteReader,
    deser_compact_size,
    ser_compact_size,
)
from ..consensus.tx import CTransaction
from ..crypto.siphash import siphash24

SHORTID_MASK = 0xFFFFFFFFFFFF  # 48 bits


def short_id_keys(header: CBlockHeader, nonce: int) -> tuple[int, int]:
    """FillShortTxIDSelector: k0/k1 = first 16 bytes of
    SHA256(serialized header || le64(nonce))."""
    digest = hashlib.sha256(
        header.serialize() + struct.pack("<Q", nonce)
    ).digest()
    k0, k1 = struct.unpack_from("<QQ", digest, 0)
    return k0, k1


def short_id(k0: int, k1: int, txid: bytes) -> int:
    """GetShortID: SipHash-2-4 of the txid, truncated to 6 bytes."""
    return siphash24(k0, k1, txid) & SHORTID_MASK


class HeaderAndShortIDs:
    """cmpctblock payload (CBlockHeaderAndShortTxIDs)."""

    def __init__(self, header: CBlockHeader, nonce: int,
                 shortids: list[int],
                 prefilled: list[tuple[int, CTransaction]]):
        self.header = header
        self.nonce = nonce
        self.shortids = shortids
        self.prefilled = prefilled  # (absolute index, tx)

    @classmethod
    def from_block(cls, block: CBlock,
                   nonce: Optional[int] = None) -> "HeaderAndShortIDs":
        """Announce form: prefill only the coinbase (like the reference's
        default CBlockHeaderAndShortTxIDs constructor)."""
        if nonce is None:
            nonce = struct.unpack("<Q", os.urandom(8))[0]
        k0, k1 = short_id_keys(block.header, nonce)
        shortids = [short_id(k0, k1, tx.txid) for tx in block.vtx[1:]]
        return cls(block.header, nonce, shortids, [(0, block.vtx[0])])

    def serialize(self) -> bytes:
        out = [self.header.serialize(), struct.pack("<Q", self.nonce),
               ser_compact_size(len(self.shortids))]
        for sid in self.shortids:
            out.append(struct.pack("<Q", sid)[:6])
        out.append(ser_compact_size(len(self.prefilled)))
        last = -1
        for index, tx in self.prefilled:
            out.append(ser_compact_size(index - last - 1))  # differential
            out.append(tx.serialize())
            last = index
        return b"".join(out)

    @classmethod
    def deserialize(cls, r: ByteReader) -> "HeaderAndShortIDs":
        header = CBlockHeader.deserialize(r)
        (nonce,) = struct.unpack("<Q", r.read_bytes(8))
        n = deser_compact_size(r)
        shortids = []
        for _ in range(n):
            shortids.append(
                struct.unpack("<Q", r.read_bytes(6) + b"\x00\x00")[0])
        n_pre = deser_compact_size(r)
        prefilled = []
        last = -1
        for _ in range(n_pre):
            diff = deser_compact_size(r)
            index = last + 1 + diff
            tx = CTransaction.deserialize(r)
            prefilled.append((index, tx))
            last = index
        return cls(header, nonce, shortids, prefilled)

    def total_tx_count(self) -> int:
        return len(self.shortids) + len(self.prefilled)

    def reconstruct(self, lookup) -> tuple[Optional[CBlock], list[int]]:
        """PartiallyDownloadedBlock::InitData + FillBlock: map short IDs to
        known txs via ``lookup`` (shortid -> CTransaction or None). Returns
        (block, []) when complete or (None, missing absolute indexes)."""
        k0, k1 = short_id_keys(self.header, self.nonce)
        total = self.total_tx_count()
        slots: list[Optional[CTransaction]] = [None] * total
        for index, tx in self.prefilled:
            if index >= total:
                return None, []
            slots[index] = tx
        sid_iter = iter(self.shortids)
        missing = []
        for i in range(total):
            if slots[i] is not None:
                continue
            sid = next(sid_iter)
            tx = lookup(sid)
            if tx is not None and short_id(k0, k1, tx.txid) == sid:
                slots[i] = tx
            else:
                missing.append(i)
        if missing:
            return None, missing
        block = CBlock(header=self.header, vtx=tuple(slots))
        return block, []


class BlockTransactionsRequest:
    """getblocktxn payload."""

    def __init__(self, block_hash: bytes, indexes: list[int]):
        self.block_hash = block_hash
        self.indexes = indexes  # absolute, ascending

    def serialize(self) -> bytes:
        out = [self.block_hash, ser_compact_size(len(self.indexes))]
        last = -1
        for i in self.indexes:
            out.append(ser_compact_size(i - last - 1))
            last = i
        return b"".join(out)

    @classmethod
    def deserialize(cls, r: ByteReader) -> "BlockTransactionsRequest":
        block_hash = r.read_bytes(32)
        n = deser_compact_size(r)
        indexes = []
        last = -1
        for _ in range(n):
            diff = deser_compact_size(r)
            last = last + 1 + diff
            indexes.append(last)
        return cls(block_hash, indexes)


class BlockTransactions:
    """blocktxn payload."""

    def __init__(self, block_hash: bytes, txs: list[CTransaction]):
        self.block_hash = block_hash
        self.txs = txs

    def serialize(self) -> bytes:
        out = [self.block_hash, ser_compact_size(len(self.txs))]
        out.extend(tx.serialize() for tx in self.txs)
        return b"".join(out)

    @classmethod
    def deserialize(cls, r: ByteReader) -> "BlockTransactions":
        block_hash = r.read_bytes(32)
        n = deser_compact_size(r)
        txs = [CTransaction.deserialize(r) for _ in range(n)]
        return cls(block_hash, txs)
