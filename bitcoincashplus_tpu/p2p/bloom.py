"""BIP37 bloom filters — SPV client filtering.

Reference: src/bloom.{h,cpp} (CBloomFilter, MurmurHash3,
IsRelevantAndUpdate), src/hash.cpp:~10 (MurmurHash3). The filter is pure
host-side peer state (tiny, branchy, per-peer) — nothing here belongs on
the chip.
"""

from __future__ import annotations

import math
import struct

from ..consensus.serialize import (
    ByteReader,
    deser_compact_size,
    ser_compact_size,
)
from ..consensus.tx import COutPoint, CTransaction

MAX_BLOOM_FILTER_SIZE = 36_000  # bytes
MAX_HASH_FUNCS = 50

# nFlags (bloom.h)
BLOOM_UPDATE_NONE = 0
BLOOM_UPDATE_ALL = 1
BLOOM_UPDATE_P2PUBKEY_ONLY = 2
BLOOM_UPDATE_MASK = 3

LN2_SQUARED = math.log(2) ** 2
LN2 = math.log(2)


def murmur3(seed: int, data: bytes) -> int:
    """MurmurHash3 x86_32 (src/hash.cpp MurmurHash3) — exact."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h1 = seed & 0xFFFFFFFF
    n_blocks = len(data) // 4
    for i in range(n_blocks):
        (k1,) = struct.unpack_from("<I", data, i * 4)
        k1 = (k1 * c1) & 0xFFFFFFFF
        k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
        h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
        h1 = (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF
    k1 = 0
    tail = data[n_blocks * 4:]
    if len(tail) >= 3:
        k1 ^= tail[2] << 16
    if len(tail) >= 2:
        k1 ^= tail[1] << 8
    if len(tail) >= 1:
        k1 ^= tail[0]
        k1 = (k1 * c1) & 0xFFFFFFFF
        k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
    h1 ^= len(data)
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1


class CBloomFilter:
    """src/bloom.cpp CBloomFilter. Construct either from (n_elements,
    fp_rate, tweak, flags) or from wire data via `from_wire`."""

    def __init__(self, n_elements: int = 1, fp_rate: float = 0.0001,
                 tweak: int = 0, flags: int = BLOOM_UPDATE_NONE):
        size = int(-1 / LN2_SQUARED * n_elements * math.log(fp_rate) / 8)
        size = max(1, min(size, MAX_BLOOM_FILTER_SIZE))
        self.data = bytearray(size)
        n_hash = int(len(self.data) * 8 / n_elements * LN2)
        self.n_hash_funcs = max(1, min(n_hash, MAX_HASH_FUNCS))
        self.tweak = tweak
        self.flags = flags

    @classmethod
    def from_wire(cls, data: bytes, n_hash_funcs: int, tweak: int,
                  flags: int) -> "CBloomFilter":
        self = cls.__new__(cls)
        self.data = bytearray(data)
        self.n_hash_funcs = n_hash_funcs
        self.tweak = tweak
        self.flags = flags
        return self

    def is_within_size_constraints(self) -> bool:
        return (len(self.data) <= MAX_BLOOM_FILTER_SIZE
                and self.n_hash_funcs <= MAX_HASH_FUNCS)

    def _hash(self, n: int, data: bytes) -> int:
        seed = (n * 0xFBA4C795 + self.tweak) & 0xFFFFFFFF
        return murmur3(seed, data) % (len(self.data) * 8)

    def insert(self, data: bytes) -> None:
        if not self.data:
            return
        for i in range(self.n_hash_funcs):
            bit = self._hash(i, data)
            self.data[bit >> 3] |= 1 << (bit & 7)

    def insert_outpoint(self, outpoint: COutPoint) -> None:
        self.insert(outpoint.hash + struct.pack("<I", outpoint.n))

    def contains(self, data: bytes) -> bool:
        if not self.data:
            return True  # a full/degenerate filter matches everything
        for i in range(self.n_hash_funcs):
            bit = self._hash(i, data)
            if not self.data[bit >> 3] & (1 << (bit & 7)):
                return False
        return True

    def contains_outpoint(self, outpoint: COutPoint) -> bool:
        return self.contains(outpoint.hash + struct.pack("<I", outpoint.n))

    def is_relevant_and_update(self, tx: CTransaction) -> bool:
        """CBloomFilter::IsRelevantAndUpdate: does this tx interest the
        filter's owner? Matching outputs are (per nFlags) inserted as
        outpoints so follow-on spends match too."""
        from ..script.script import classify_script, get_script_ops

        found = False
        if not self.data:
            return True
        if self.contains(tx.txid):
            found = True
        for i, out in enumerate(tx.vout):
            matched = False
            try:
                for _op, push, _ in get_script_ops(out.script_pubkey):
                    if push and self.contains(bytes(push)):
                        matched = True
                        break
            except Exception:
                pass  # unparseable script: no data elements to match
            if matched:
                found = True
                update = self.flags & BLOOM_UPDATE_MASK
                if update == BLOOM_UPDATE_ALL:
                    self.insert_outpoint(COutPoint(tx.txid, i))
                elif update == BLOOM_UPDATE_P2PUBKEY_ONLY:
                    if classify_script(out.script_pubkey) in ("pubkey",
                                                              "multisig"):
                        self.insert_outpoint(COutPoint(tx.txid, i))
        if found:
            return True
        for txin in tx.vin:
            if self.contains_outpoint(txin.prevout):
                return True
            try:
                for _op, push, _ in get_script_ops(txin.script_sig):
                    if push and self.contains(bytes(push)):
                        return True
            except Exception:
                pass
        return False


# ---- wire codecs (filterload / filteradd) -----------------------------


def ser_filterload(f: CBloomFilter) -> bytes:
    return (ser_compact_size(len(f.data)) + bytes(f.data)
            + struct.pack("<IIB", f.n_hash_funcs, f.tweak, f.flags))


def deser_filterload(payload: bytes) -> CBloomFilter:
    r = ByteReader(payload)
    n = deser_compact_size(r)
    data = r.read_bytes(n)
    n_hash, tweak, flags = struct.unpack("<IIB", r.read_bytes(9))
    return CBloomFilter.from_wire(data, n_hash, tweak, flags)
