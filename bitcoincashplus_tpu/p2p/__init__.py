"""P2P networking — wire protocol + connection manager.

Reference: src/protocol.{h,cpp} (CMessageHeader, CInv), src/net.{h,cpp}
(CConnman), src/net_processing.cpp (ProcessMessage/SendMessages). Minimal
viable subset (SURVEY.md §3.1 plan): version/verack/ping/pong/inv/getdata/
getheaders/headers/block/tx with the 24-byte SHA256d-checksum framing.
"""

from .protocol import MessageHeader, NetMessageError  # noqa: F401
from .connman import CConnman  # noqa: F401
