"""Wire protocol: message framing + payload codecs.

Reference: src/protocol.h (CMessageHeader: 4B netmagic, 12B NUL-padded
command, u32 payload length, 4B SHA256d checksum; CInv: u32 type + 32B
hash), src/version.h (PROTOCOL_VERSION), message payload layouts from
src/net_processing.cpp / primitives serialization.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

from ..consensus.serialize import (
    ByteReader,
    deser_compact_size,
    ser_compact_size,
)
from ..crypto.hashes import sha256d

PROTOCOL_VERSION = 70015
NODE_NETWORK = 1
MAX_PAYLOAD_SIZE = 32 * 1024 * 1024  # MAX_PROTOCOL_MESSAGE_LENGTH ballpark
MAX_HEADERS_RESULTS = 2000  # MAX_HEADERS_RESULTS (net_processing.cpp)
MAX_LOCATOR_SZ = 101

# CInv types (src/protocol.h)
MSG_TX = 1
MSG_BLOCK = 2
MSG_FILTERED_BLOCK = 3  # BIP37: getdata answered with merkleblock
MSG_CMPCT_BLOCK = 4     # BIP152: getdata answered with cmpctblock

HEADER_SIZE = 24


class NetMessageError(Exception):
    """Malformed wire data. Raising this always ends the connection; the
    ``score`` is what gets recorded on the sender's ban-score ledger for
    the event (connman.CConnman.misbehaving). The default of 100 matches
    the ledger's default threshold, so an un-annotated raise records an
    immediate discharge — the historical behavior. score=0 marks a benign
    protocol disconnect (self-connect, duplicate version): the connection
    still ends but nothing reaches the ledger or the attack counters. A
    raise with a lower positive score would disconnect WITHOUT recording
    a discharge; truly graduated (accumulating) offenses must instead
    charge via misbehaving() and return, since a per-connection ledger
    resets on reconnect."""

    def __init__(self, message: str, score: int = 100):
        super().__init__(message)
        self.score = score


@dataclass
class MessageHeader:
    magic: bytes
    command: str
    length: int
    checksum: bytes

    @classmethod
    def parse(cls, raw: bytes, expect_magic: bytes) -> "MessageHeader":
        if len(raw) != HEADER_SIZE:
            raise NetMessageError("short header")
        magic = raw[:4]
        if magic != expect_magic:
            raise NetMessageError(f"bad netmagic {magic.hex()}")
        cmd_raw = raw[4:16]
        cmd = cmd_raw.rstrip(b"\x00")
        if b"\x00" in cmd or not cmd.isascii():
            raise NetMessageError("non-canonical command field")
        (length,) = struct.unpack_from("<I", raw, 16)
        if length > MAX_PAYLOAD_SIZE:
            raise NetMessageError(f"oversized payload {length}")
        return cls(magic, cmd.decode("ascii"), length, raw[20:24])


def pack_message(magic: bytes, command: str, payload: bytes = b"") -> bytes:
    cmd = command.encode("ascii")
    if len(cmd) > 12:
        raise ValueError(f"command too long: {command}")
    return (
        magic + cmd.ljust(12, b"\x00")
        + struct.pack("<I", len(payload))
        + sha256d(payload)[:4]
        + payload
    )


def check_payload(header: MessageHeader, payload: bytes) -> None:
    if sha256d(payload)[:4] != header.checksum:
        raise NetMessageError(f"bad checksum for {header.command}")


# ---- payload codecs ---------------------------------------------------


def _ser_netaddr(services: int = NODE_NETWORK, port: int = 0) -> bytes:
    """CAddress sans time (as used inside `version`): loopback v4-mapped."""
    ip = b"\x00" * 10 + b"\xff\xff" + bytes([127, 0, 0, 1])
    return struct.pack("<Q", services) + ip + struct.pack(">H", port)


@dataclass
class VersionPayload:
    version: int = PROTOCOL_VERSION
    services: int = NODE_NETWORK
    timestamp: int = field(default_factory=lambda: int(time.time()))
    nonce: int = 0
    user_agent: str = "/bcpd-tpu:0.4.0/"
    start_height: int = 0
    relay: bool = True

    def serialize(self) -> bytes:
        ua = self.user_agent.encode()
        return (
            struct.pack("<iQq", self.version, self.services, self.timestamp)
            + _ser_netaddr(self.services)
            + _ser_netaddr(self.services)
            + struct.pack("<Q", self.nonce)
            + ser_compact_size(len(ua)) + ua
            + struct.pack("<i", self.start_height)
            + (b"\x01" if self.relay else b"\x00")
        )

    @classmethod
    def parse(cls, payload: bytes) -> "VersionPayload":
        try:
            r = ByteReader(payload)
            version, services, timestamp = struct.unpack("<iQq", r.read_bytes(20))
            r.read_bytes(26 * 2)  # addr_recv, addr_from
            (nonce,) = struct.unpack("<Q", r.read_bytes(8))
            ua_len = deser_compact_size(r)
            ua = r.read_bytes(ua_len).decode(errors="replace")
            (start_height,) = struct.unpack("<i", r.read_bytes(4))
            relay = bool(r.read_bytes(1)[0]) if not r.empty() else True
        except Exception as e:
            raise NetMessageError(f"bad version payload: {e}") from None
        return cls(version, services, timestamp, nonce, ua, start_height, relay)


def ser_inv(items: list[tuple[int, bytes]]) -> bytes:
    out = [ser_compact_size(len(items))]
    for inv_type, h in items:
        out.append(struct.pack("<I", inv_type) + h)
    return b"".join(out)


def deser_inv(payload: bytes) -> list[tuple[int, bytes]]:
    try:
        r = ByteReader(payload)
        n = deser_compact_size(r)
        if n > 50_000:  # MAX_INV_SZ
            raise NetMessageError("oversized inv")
        items = []
        for _ in range(n):
            (inv_type,) = struct.unpack("<I", r.read_bytes(4))
            items.append((inv_type, r.read_bytes(32)))
        return items
    except NetMessageError:
        raise
    except Exception as e:
        raise NetMessageError(f"bad inv: {e}") from None


def ser_getheaders(locator: list[bytes], hash_stop: bytes = b"\x00" * 32) -> bytes:
    out = [struct.pack("<I", PROTOCOL_VERSION), ser_compact_size(len(locator))]
    out.extend(locator)
    out.append(hash_stop)
    return b"".join(out)


def deser_getheaders(payload: bytes) -> tuple[list[bytes], bytes]:
    try:
        r = ByteReader(payload)
        r.read_bytes(4)  # client version, unused
        n = deser_compact_size(r)
        if n > MAX_LOCATOR_SZ:
            raise NetMessageError("oversized locator")
        locator = [r.read_bytes(32) for _ in range(n)]
        return locator, r.read_bytes(32)
    except NetMessageError:
        raise
    except Exception as e:
        raise NetMessageError(f"bad getheaders: {e}") from None


def ser_headers(headers: list) -> bytes:
    """headers message: each entry is an 80B header + 00 tx count."""
    out = [ser_compact_size(len(headers))]
    for h in headers:
        out.append(h.serialize() + b"\x00")
    return b"".join(out)


def deser_headers(payload: bytes) -> list:
    from ..consensus.block import CBlockHeader

    try:
        r = ByteReader(payload)
        n = deser_compact_size(r)
        if n > MAX_HEADERS_RESULTS:
            raise NetMessageError("too many headers")
        headers = []
        for _ in range(n):
            headers.append(CBlockHeader.deserialize(r))
            deser_compact_size(r)  # tx count, always 0
        return headers
    except NetMessageError:
        raise
    except Exception as e:
        raise NetMessageError(f"bad headers: {e}") from None


def ser_ping(nonce: int) -> bytes:
    return struct.pack("<Q", nonce)


def deser_ping(payload: bytes) -> int:
    if len(payload) != 8:
        raise NetMessageError("bad ping")
    return struct.unpack("<Q", payload)[0]


# ---- addr message (CAddress with time, src/protocol.h) ---------------


def ser_addr_entries(entries: list[tuple[int, int, str, int]]) -> bytes:
    """addr payload: [(time, services, ipv4_host, port), ...]."""
    out = [ser_compact_size(len(entries))]
    for t, services, host, port in entries:
        try:
            ip4 = bytes(int(x) for x in host.split("."))
            if len(ip4) != 4:
                raise ValueError(host)
        except Exception:
            ip4 = bytes([127, 0, 0, 1])
        out.append(struct.pack("<IQ", t & 0xFFFFFFFF, services)
                   + b"\x00" * 10 + b"\xff\xff" + ip4
                   + struct.pack(">H", port))
    return b"".join(out)


def deser_addr_entries(payload: bytes) -> list[tuple[int, int, str, int]]:
    try:
        r = ByteReader(payload)
        n = deser_compact_size(r)
        if n > 1000:  # MAX_ADDR_TO_SEND
            raise NetMessageError("oversized addr")
        out = []
        for _ in range(n):
            t, services = struct.unpack("<IQ", r.read_bytes(12))
            ip = r.read_bytes(16)
            (port,) = struct.unpack(">H", r.read_bytes(2))
            if ip[:12] == b"\x00" * 10 + b"\xff\xff":  # v4-mapped
                host = ".".join(str(b) for b in ip[12:])
            else:
                host = "::"  # v6 unsupported in this deployment
            out.append((t, services, host, port))
        return out
    except NetMessageError:
        raise
    except Exception as e:
        raise NetMessageError(f"bad addr: {e}") from None
