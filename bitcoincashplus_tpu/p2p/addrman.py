"""Address manager — known-peer bookkeeping + peers.dat persistence.

Reference: src/addrman.{h,cpp} (CAddrMan: new/tried tables, Select/Good/
Attempt/Add), src/net.cpp (DumpAddresses/LoadAddresses via CAddrDB →
peers.dat). The reference's 1024/256 bucketed eclipse-resistance layout is
collapsed to flat new/tried sets with the same lifecycle — the bucketing
defends against internet-scale eclipse attacks, which a loopback/test
deployment cannot exhibit; the API and persistence contract are kept so a
bucketed implementation can drop in.
"""

from __future__ import annotations

import json
import os
import random
import time
from typing import Optional


class AddrInfo:
    __slots__ = ("host", "port", "services", "time", "attempts",
                 "last_try", "tried")

    def __init__(self, host: str, port: int, services: int = 1,
                 seen_time: Optional[int] = None):
        self.host = host
        self.port = port
        self.services = services
        self.time = seen_time if seen_time is not None else int(time.time())
        self.attempts = 0
        self.last_try = 0.0
        self.tried = False

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"

    def to_dict(self) -> dict:
        return {"host": self.host, "port": self.port,
                "services": self.services, "time": self.time,
                "attempts": self.attempts, "tried": self.tried}

    @classmethod
    def from_dict(cls, d: dict) -> "AddrInfo":
        a = cls(d["host"], int(d["port"]), int(d.get("services", 1)),
                int(d.get("time", 0)))
        # attempts deliberately reset: a restart gives every stored
        # address a fresh chance (the failure history was this-session)
        a.tried = bool(d.get("tried", False))
        return a


# horizon/retry limits (addrman.h ADDRMAN_* constants)
HORIZON_DAYS = 30
MAX_RETRIES = 3
MAX_ADDRESSES = 1000  # per getaddr reply (MAX_ADDR_TO_SEND, net.h)
# total table bound (Core bounds via 1024 new + 256 tried buckets × 64);
# overflow evicts random untried entries so a hostile peer can't grow the
# table or peers.json without limit
MAX_TABLE_SIZE = 4096


class AddrMan:
    def __init__(self):
        self.addrs: dict[str, AddrInfo] = {}
        self._rng = random.Random()

    def __len__(self) -> int:
        return len(self.addrs)

    def add(self, host: str, port: int, services: int = 1,
            seen_time: Optional[int] = None) -> bool:
        """CAddrMan::Add — new address into the 'new' side; refreshes the
        timestamp of a known one."""
        info = AddrInfo(host, port, services, seen_time)
        cur = self.addrs.get(info.key)
        if cur is None:
            if len(self.addrs) >= MAX_TABLE_SIZE:
                untried = [k for k, a in self.addrs.items() if not a.tried]
                if not untried:
                    return False  # table full of good peers: drop the new one
                self.addrs.pop(self._rng.choice(untried))
            self.addrs[info.key] = info
            return True
        cur.time = max(cur.time, info.time)
        cur.services |= services
        return False

    def attempt(self, host: str, port: int) -> None:
        cur = self.addrs.get(f"{host}:{port}")
        if cur is not None:
            cur.attempts += 1
            cur.last_try = time.time()

    def good(self, host: str, port: int) -> None:
        """CAddrMan::Good — successful handshake moves it to 'tried'."""
        cur = self.addrs.get(f"{host}:{port}")
        if cur is None:
            cur = AddrInfo(host, port)
            self.addrs[cur.key] = cur
        cur.tried = True
        cur.attempts = 0
        cur.time = int(time.time())

    def select(self, exclude: Optional[set[str]] = None) -> Optional[AddrInfo]:
        """CAddrMan::Select — pick a dial candidate, preferring tried,
        skipping recently failed and excluded (connected) addresses."""
        exclude = exclude or set()
        now = time.time()
        # IsTerrible is time-windowed in the reference, not permanent:
        # past MAX_RETRIES an address still gets another chance once an
        # hour, so a transiently-down peer is eventually redialed
        candidates = [
            a for a in self.addrs.values()
            if a.key not in exclude
            and (a.attempts <= MAX_RETRIES or now - a.last_try > 3600)
            and now - a.last_try > 10 * min(a.attempts + 1, 6)
        ]
        if not candidates:
            return None
        tried = [a for a in candidates if a.tried]
        pool = tried if tried and self._rng.random() < 0.5 else candidates
        return self._rng.choice(pool)

    def addresses(self, max_count: int = MAX_ADDRESSES) -> list[AddrInfo]:
        """GetAddr: a random sample for getaddr replies, fresh ones only."""
        horizon = time.time() - HORIZON_DAYS * 86400
        fresh = [a for a in self.addrs.values() if a.time > horizon]
        self._rng.shuffle(fresh)
        return fresh[:max_count]

    # -- persistence (peers.dat role; json like the wallet/mempool) ------

    def save(self, path: str) -> None:
        tmp = path + ".new"
        with open(tmp, "w") as f:
            json.dump({"version": 1,
                       "addrs": [a.to_dict() for a in self.addrs.values()]},
                      f)
        os.replace(tmp, path)

    def load(self, path: str) -> int:
        if not os.path.exists(path):
            return 0
        try:
            with open(path) as f:
                payload = json.load(f)
            for d in payload.get("addrs", []):
                a = AddrInfo.from_dict(d)
                self.addrs[a.key] = a
        except (OSError, ValueError, KeyError):
            return 0  # corrupt peers file must never stop the node
        return len(self.addrs)
