"""Address manager — bucketed known-peer bookkeeping + peers.dat persistence.

Reference: src/addrman.{h,cpp} (CAddrMan: 1024 new / 256 tried buckets of 64
slots, per-source-group bucketing, Select/Good/Attempt/Add), src/net.cpp
(DumpAddresses/LoadAddresses via CAddrDB → peers.dat).

Eclipse resistance comes from the INSERTION constraints, reproduced here:
  - a (new) address's bucket is derived from sip-hashing (secret key,
    address group, SOURCE group) — one source group can reach at most
    64 of the 1024 new buckets, so a single attacker announcing thousands
    of addresses can fill at most 64*64 slots, not the table;
  - a full slot is only re-used when its incumbent is stale/terrible, so
    flooding cannot displace healthy addresses;
  - tried placement keys off the address itself; a collision displaces the
    incumbent back to the new table (the pre-test-before-evict reference
    behavior) rather than silently dropping either.

Documented simplifications vs the reference: one new-table reference per
address (the reference allows up to 8 via distinct sources), and Select
walks the eligible-entry set directly instead of random bucket probing —
the bucket layout constrains CAPACITY and placement (the eclipse defense);
selection fairness differences at loopback scale are noise.
"""

from __future__ import annotations

import json
import os
import random
import time
from typing import Optional

from ..crypto.siphash import siphash24

NEW_BUCKETS = 1024
TRIED_BUCKETS = 256
BUCKET_SIZE = 64
NEW_BUCKETS_PER_SOURCE_GROUP = 64
TRIED_BUCKETS_PER_GROUP = 8

# horizon/retry limits (addrman.h ADDRMAN_* constants)
HORIZON_DAYS = 30
MAX_RETRIES = 3
MAX_ADDRESSES = 1000  # per getaddr reply (MAX_ADDR_TO_SEND, net.h)


class AddrInfo:
    __slots__ = ("host", "port", "services", "time", "attempts",
                 "last_try", "tried", "source")

    def __init__(self, host: str, port: int, services: int = 1,
                 seen_time: Optional[int] = None,
                 source: Optional[str] = None):
        self.host = host
        self.port = port
        self.services = services
        self.time = seen_time if seen_time is not None else int(time.time())
        self.attempts = 0
        self.last_try = 0.0
        self.tried = False
        self.source = source if source is not None else host

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"

    def to_dict(self) -> dict:
        return {"host": self.host, "port": self.port,
                "services": self.services, "time": self.time,
                "attempts": self.attempts, "tried": self.tried,
                "source": self.source}

    @classmethod
    def from_dict(cls, d: dict) -> "AddrInfo":
        a = cls(d["host"], int(d["port"]), int(d.get("services", 1)),
                int(d.get("time", 0)), d.get("source"))
        # attempts deliberately reset: a restart gives every stored
        # address a fresh chance (the failure history was this-session)
        a.tried = bool(d.get("tried", False))
        return a


def _group(host: str) -> str:
    """Network group (netaddress GetGroup): /16 for IPv4, the literal host
    otherwise (IPv6/onion grouping collapsed — loopback deployments)."""
    parts = host.split(".")
    if len(parts) == 4 and all(p.isdigit() for p in parts):
        return parts[0] + "." + parts[1]
    return host


class AddrMan:
    def __init__(self, seed: Optional[int] = None):
        self.addrs: dict[str, AddrInfo] = {}
        self._rng = random.Random(seed)
        # nKey — the secret bucketing key (persisted: rebucketing on every
        # restart would let an observer correlate placements). Bucket
        # placement is the eclipse defense, so the key comes from a CSPRNG
        # like the reference's nKey (ADVICE r4) — the deterministic seed
        # stays test-only.
        if seed is None:
            import secrets

            self._k0 = secrets.randbits(64)
            self._k1 = secrets.randbits(64)
        else:
            self._k0 = self._rng.getrandbits(64)
            self._k1 = self._rng.getrandbits(64)
        # (bucket, slot) -> addr key; inverse position map on the side
        self.new_tbl: dict[tuple, str] = {}
        self.tried_tbl: dict[tuple, str] = {}
        self._pos: dict[str, tuple] = {}  # addr key -> ("new"/"tried", b, s)

    def __len__(self) -> int:
        return len(self.addrs)

    # -- bucket math (CAddrMan::GetNewBucket/GetTriedBucket) -------------

    def _h(self, *parts: str) -> int:
        return siphash24(self._k0, self._k1, "|".join(parts).encode())

    def _new_bucket(self, host: str, source: str) -> int:
        h1 = self._h("N1", _group(host), _group(source)) \
            % NEW_BUCKETS_PER_SOURCE_GROUP
        return self._h("N2", _group(source), str(h1)) % NEW_BUCKETS

    def _tried_bucket(self, key: str, host: str) -> int:
        h1 = self._h("T1", key) % TRIED_BUCKETS_PER_GROUP
        return self._h("T2", _group(host), str(h1)) % TRIED_BUCKETS

    def _slot(self, table: str, bucket: int, key: str) -> int:
        return self._h("S", table, str(bucket), key) % BUCKET_SIZE

    def _is_terrible(self, info: AddrInfo, now: Optional[float] = None) -> bool:
        """CAddrInfo::IsTerrible — eviction eligibility for a slot
        incumbent."""
        now = now if now is not None else time.time()
        if info.time > now + 600:
            return True  # nonsense future timestamp
        if info.time < now - HORIZON_DAYS * 86400:
            return True  # over the horizon
        return info.attempts >= MAX_RETRIES

    # -- table surgery ---------------------------------------------------

    def _drop(self, key: str) -> None:
        pos = self._pos.pop(key, None)
        if pos is not None:
            tbl = self.new_tbl if pos[0] == "new" else self.tried_tbl
            tbl.pop((pos[1], pos[2]), None)
        self.addrs.pop(key, None)

    def _place_new(self, info: AddrInfo, force: bool = False) -> bool:
        """Insert into the new table; False = dropped (healthy incumbent).
        ``force`` evicts the incumbent regardless — used when re-homing a
        PROVEN-good address displaced from the tried table, which must not
        lose to an unvetted gossip entry (CAddrMan::MakeTried clears the
        slot for the demotee)."""
        b = self._new_bucket(info.host, info.source)
        s = self._slot("new", b, info.key)
        incumbent_key = self.new_tbl.get((b, s))
        if incumbent_key is not None and incumbent_key != info.key:
            incumbent = self.addrs.get(incumbent_key)
            if (not force and incumbent is not None
                    and not self._is_terrible(incumbent)):
                return False  # slot defended: the flood is absorbed here
            self._drop(incumbent_key)
        self.new_tbl[(b, s)] = info.key
        self._pos[info.key] = ("new", b, s)
        self.addrs[info.key] = info
        return True

    # -- public lifecycle (Add/Attempt/Good/Select) ----------------------

    def add(self, host: str, port: int, services: int = 1,
            seen_time: Optional[int] = None,
            source: Optional[str] = None) -> bool:
        """CAddrMan::Add — new address into the 'new' side; refreshes the
        timestamp of a known one. ``source`` is the gossiping peer (the
        eclipse-critical input: it picks which 64 buckets are reachable)."""
        info = AddrInfo(host, port, services, seen_time, source)
        cur = self.addrs.get(info.key)
        if cur is not None:
            cur.time = max(cur.time, info.time)
            cur.services |= services
            return False
        return self._place_new(info)

    def attempt(self, host: str, port: int) -> None:
        cur = self.addrs.get(f"{host}:{port}")
        if cur is not None:
            cur.attempts += 1
            cur.last_try = time.time()

    def good(self, host: str, port: int) -> None:
        """CAddrMan::Good — successful handshake moves it to 'tried'. A
        tried-slot collision displaces the incumbent back to the new table
        (reference pre-test-before-evict semantics)."""
        key = f"{host}:{port}"
        cur = self.addrs.get(key)
        if cur is None:
            cur = AddrInfo(host, port)
            if not self._place_new(cur):
                return  # table defended the slot; nothing to promote
        cur.attempts = 0
        cur.time = int(time.time())
        if cur.tried:
            return  # already in tried
        b = self._tried_bucket(key, host)
        s = self._slot("tried", b, key)
        incumbent_key = self.tried_tbl.get((b, s))
        # leave the new table
        pos = self._pos.pop(key, None)
        if pos is not None and pos[0] == "new":
            self.new_tbl.pop((pos[1], pos[2]), None)
        if incumbent_key is not None and incumbent_key != key:
            incumbent = self.addrs.get(incumbent_key)
            self.tried_tbl.pop((b, s), None)
            self._pos.pop(incumbent_key, None)
            if incumbent is not None:
                # demoted-but-proven address: force-home it in the new
                # table (it must beat any unvetted gossip incumbent)
                incumbent.tried = False
                self._place_new(incumbent, force=True)
        cur.tried = True
        self.tried_tbl[(b, s)] = key
        self._pos[key] = ("tried", b, s)

    def select(self, exclude: Optional[set[str]] = None) -> Optional[AddrInfo]:
        """CAddrMan::Select — pick a dial candidate, preferring tried,
        skipping recently failed and excluded (connected) addresses."""
        exclude = exclude or set()
        now = time.time()
        # IsTerrible is time-windowed in the reference, not permanent:
        # past MAX_RETRIES an address still gets another chance once an
        # hour, so a transiently-down peer is eventually redialed
        candidates = [
            a for a in self.addrs.values()
            if a.key not in exclude
            and (a.attempts <= MAX_RETRIES or now - a.last_try > 3600)
            and now - a.last_try > 10 * min(a.attempts + 1, 6)
        ]
        if not candidates:
            return None
        tried = [a for a in candidates if a.tried]
        pool = tried if tried and self._rng.random() < 0.5 else candidates
        return self._rng.choice(pool)

    def addresses(self, max_count: int = MAX_ADDRESSES) -> list[AddrInfo]:
        """GetAddr: a random sample for getaddr replies, fresh ones only."""
        horizon = time.time() - HORIZON_DAYS * 86400
        fresh = [a for a in self.addrs.values() if a.time > horizon]
        self._rng.shuffle(fresh)
        return fresh[:max_count]

    # -- persistence (peers.dat role; json like the wallet/mempool) ------

    def save(self, path: str) -> None:
        tmp = path + ".new"
        with open(tmp, "w") as f:
            json.dump({"version": 2,
                       "key": [self._k0, self._k1],
                       "addrs": [a.to_dict() for a in self.addrs.values()]},
                      f)
        os.replace(tmp, path)

    def load(self, path: str) -> int:
        if not os.path.exists(path):
            return 0
        try:
            with open(path) as f:
                payload = json.load(f)
            key = payload.get("key")
            if isinstance(key, list) and len(key) == 2:
                self._k0, self._k1 = int(key[0]), int(key[1])
            for d in payload.get("addrs", []):
                a = AddrInfo.from_dict(d)
                was_tried = a.tried
                a.tried = False
                if not self._place_new(a):
                    continue  # bucket collision on load: drop, like CAddrDB
                if was_tried:
                    self.good(a.host, a.port)
                    got = self.addrs.get(a.key)
                    if got is not None:
                        got.time = a.time  # good() stamped now; restore
                        got.services = a.services
        except (OSError, ValueError, KeyError):
            return 0  # corrupt peers file must never stop the node
        return len(self.addrs)
