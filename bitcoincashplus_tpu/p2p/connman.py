"""Connection manager + message processing.

Reference: src/net.cpp (CConnman: accept loop, peer lifecycle — the
reference's ThreadSocketHandler/ThreadMessageHandler pair is one asyncio
event loop on a dedicated thread here), src/net_processing.cpp
(ProcessMessage: the per-command logic below follows its shape, minimal
subset; headers-first sync as in the reference's getheaders/headers/
getdata flow). Chainstate/mempool access happens under node.cs_main.

Fault handling (net_processing.cpp DoS machinery): every protocol reject
site charges the sending peer's ban-score ledger via misbehaving(score,
reason) — framing errors and invalid blocks discharge immediately (score
100 >= threshold), while recoverable offenses (non-connecting headers,
invalid txs, receive-rate floods, withheld blocks) accumulate until the
configurable threshold evicts the peer. Per-peer in-flight block tracking
with stall detection re-requests withheld blocks from another peer
(BLOCK_DOWNLOAD_TIMEOUT), the orphan pool is byte-budgeted with
seeded-random eviction and per-peer attribution, and the banlist persists
across restarts (banlist.json, banman.cpp DumpBanlist/LoadBanlist).
"""

from __future__ import annotations

import asyncio
import os
import random
import secrets
import struct
import threading
import time
from typing import Optional

from ..consensus.block import CBlock
from ..consensus.serialize import hash_to_hex
from ..consensus.tx import CTransaction
from ..consensus.pow import check_headers_pow_batch
from ..mempool.mempool import MempoolError
from ..store.kvstore import atomic_write_json, read_json
from ..util import lockwatch
from ..util import telemetry as tm
from ..util.faults import INJECTOR, Backoff, InjectedFault, NET_SITE
from ..util.log import log_print, log_printf
from ..validation.chain import BlockStatus
from ..validation.chainstate import BlockValidationError
from .bloom import (
    MAX_BLOOM_FILTER_SIZE,
    CBloomFilter,
    deser_filterload,
)
from .protocol import (
    HEADER_SIZE,
    MAX_HEADERS_RESULTS,
    MSG_BLOCK,
    MSG_CMPCT_BLOCK,
    MSG_FILTERED_BLOCK,
    MSG_TX,
    MessageHeader,
    NetMessageError,
    VersionPayload,
    check_payload,
    deser_getheaders,
    deser_headers,
    deser_inv,
    deser_ping,
    pack_message,
    ser_getheaders,
    ser_headers,
    ser_inv,
    ser_ping,
)


MAX_ORPHAN_TX = 100  # DEFAULT_MAX_ORPHAN_TRANSACTIONS
MAX_ORPHAN_BYTES = 500_000   # byte budget for the whole orphan pool
MAX_ORPHAN_TX_SIZE = 100_000  # larger orphans are dropped outright
ORPHAN_EXPIRE_TIME = 1200    # ORPHAN_TX_EXPIRE_TIME (20 min)
PING_INTERVAL = 120       # net.cpp PING_INTERVAL
TIMEOUT_INTERVAL = 1200   # net.cpp TIMEOUT_INTERVAL (20 min)
RELAY_TX_CACHE_TIME = 900  # mapRelay retention (15 min, net_processing.cpp)

# Misbehavior charges (net_processing.cpp Misbehaving call sites). A
# NetMessageError's own ``score`` covers the raise-sites; these cover the
# graduated, non-fatal ones. Values are fractions of the default 100
# threshold — see README "Adversarial peers & DoS limits".
CHARGE_NONCONNECTING_HEADERS = 10  # unsolicited headers on unknown parent
CHARGE_INVALID_TX = 10             # consensus-invalid tx (not policy/fee)
CHARGE_RECV_FLOOD = 25             # one tick over the receive-rate ceiling
# "bad-txns-*" reject reasons that are POLICY or subjective to our own
# chain state, never misbehavior — an honest relayer hits all of these in
# normal operation (mempool/accept.py raises them only on the mempool
# path; the block-connect versions of in-belowout etc. stay consensus)
POLICY_BAD_TXNS = frozenset({
    "bad-txns-nonstandard-inputs",           # input standardness (policy)
    "bad-txns-too-many-sigops",              # MAX_STANDARD_TX_SIGOPS cap
    "bad-txns-premature-spend-of-coinbase",  # subjective to our height
})
# Default rate limit on the non-connecting-headers charge
# (MAX_UNCONNECTING_HEADERS): an honest peer hits the offense in bursts
# (tip announcements racing a reorg, announcements during our own IBD), so
# only every Nth occurrence since the peer last taught us a NEW connecting
# header is charged — a garbage-replayer still accumulates to the
# threshold, an honest peer's counter keeps getting reset and never does
# (replaying already-known headers is not redemption). Tunable via
# -maxunconnectingheaders (tests pin 1 to drive the graduated path fast).
MAX_UNCONNECTING_HEADERS = 10

# BIP61 reject codes (src/consensus/validation.h REJECT_*)
REJECT_MALFORMED = 0x01
REJECT_INVALID = 0x10
REJECT_DUPLICATE = 0x12
REJECT_NONSTANDARD = 0x40
REJECT_INSUFFICIENTFEE = 0x42

class Peer:
    """CNode — one connected peer."""

    _next_id = 0

    def __init__(self, connman: "CConnman", reader, writer, outbound: bool):
        Peer._next_id += 1
        self.id = Peer._next_id
        self.connman = connman
        self.reader = reader
        self.writer = writer
        self.outbound = outbound
        peername = writer.get_extra_info("peername") or ("?", 0)
        self.addr = f"{peername[0]}:{peername[1]}"
        self.version: Optional[VersionPayload] = None
        self.got_verack = False
        self.prefers_headers = False  # BIP130 sendheaders
        # BIP37 SPV state: None = no filter (relay per relay_txs);
        # set by filterload, updated by matches per nFlags
        self.bloom_filter: Optional[CBloomFilter] = None
        # fRelayTxes: seeded from the version message's relay byte;
        # filterload/filterclear force it back on (BIP37 semantics)
        self.relay_txs = True
        # BIP152: peer sent sendcmpct(announce=1) → announce new tips as
        # cmpctblock (high-bandwidth mode)
        self.cmpct_announce = False
        # one in-flight compact-block reconstruction (PartiallyDownloadedBlock)
        self.pending_cmpct = None
        # BIP133 feefilter: don't announce txs below this rate (sat/kB)
        self.min_fee_filter = 0
        self.known_invs: set[bytes] = set()
        self.connected_at = time.time()
        self.last_recv = 0.0
        self.last_send = 0.0
        self.bytes_recv = 0
        self.bytes_sent = 0
        # -- ban-score ledger (net_processing.cpp CNodeState::nMisbehavior)
        self.ban_score = 0
        self.charges: dict[str, int] = {}  # reason -> accumulated score
        self.discharged = False            # threshold crossed, eviction due
        # -- block-download state (CNodeState vBlocksInFlight)
        self.inflight: set[bytes] = set()  # block hashes getdata'd, unseen
        self.last_block_progress = 0.0     # last getdata sent / block recvd
        self.stalling = False
        self.stalling_since = 0.0
        self.stall_charge = 0  # provisional charge, rolled back on redeem
        # non-connecting headers messages since the last connecting one
        # (CNodeState::nUnconnectingHeaders)
        self.unconnecting_headers = 0
        # -- receive-rate accounting (per-tick window)
        self.recv_window = 0   # bytes received in the current tick window
        self.recv_rate = 0.0   # bytes/sec over the last completed window
        self.flood_strikes = 0
        self.last_ping_sent = self.connected_at

    @property
    def handshaked(self) -> bool:
        return self.version is not None and self.got_verack

    def send(self, command: str, payload: bytes = b"") -> None:
        raw = pack_message(self.connman.magic, command, payload)
        self.writer.write(raw)
        self.bytes_sent += len(raw)
        self.connman.bytes_sent += len(raw)
        self.last_send = time.time()

    def info(self) -> dict:
        """getpeerinfo row (src/rpc/net.cpp)."""
        return {
            "id": self.id,
            "addr": self.addr,
            "inbound": not self.outbound,
            "version": self.version.version if self.version else 0,
            "subver": self.version.user_agent if self.version else "",
            "startingheight": self.version.start_height if self.version else -1,
            "conntime": int(self.connected_at),
            "bytessent": self.bytes_sent,
            "bytesrecv": self.bytes_recv,
            # ban-score ledger + download/rate state (this framework's
            # DoS observability; the reference exposes banscore too)
            "banscore": self.ban_score,
            "charges": dict(self.charges),
            "inflight": len(self.inflight),
            "stalling": self.stalling,
            "recvrate": round(self.recv_rate, 1),
            "floodstrikes": self.flood_strikes,
        }


# telemetry: supervision-tick duration — a tick that blocks the event
# loop shows up here long before peers start timing out
_TICK_H = tm.histogram(
    "bcp_net_tick_seconds",
    "P2P supervision tick (_tick) duration",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 0.5, 1.0))


class CConnman:
    # machine-enforced by bcplint BCP009: every static write site (and
    # every caller-holds path the reachability analysis can see) must
    # hold the named lock. _ban_seq is bumped in _snapshot_banlist with
    # ban_lock held by the caller — the interprocedural lockset proves
    # it, so the convention is checked, not just documented.
    GUARDED_BY = {
        "_banned": "ban_lock",
        "_ban_seq": "ban_lock",
        "_ban_saved_seq": "ban_io_lock",
    }

    def __init__(self, node, bind_host: str = "127.0.0.1", listen_port: int = 0):
        self.node = node
        self.magic = node.params.netmagic
        self.bind_host = bind_host
        self.listen_port = listen_port  # 0 = don't listen
        self.port = 0
        self.peers: dict[int, Peer] = {}
        self.bytes_recv = 0
        self.bytes_sent = 0
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        # in-flight block downloads: hash -> requesting peer id. Entries are
        # dropped on block arrival; a stalled or disconnected peer's
        # entries are re-requested from another peer (or dropped when none
        # remain) — otherwise one wedged peer would leave the hash
        # "requested" forever and sync would deadlock.
        self._requested_blocks: dict[bytes, int] = {}
        # blocks we know we need but currently have no peer to ask (their
        # owner stalled/disconnected and every other announcer is busy);
        # _tick re-requests them as soon as an announcer is available
        self._unrequested: set[bytes] = set()
        # hash -> ids of peers whose announcement (headers batch or
        # cmpctblock) included it. Re-requests are routed ONLY to
        # announcers: a peer can only be held accountable (stall charges,
        # eviction) for blocks it claimed to have — handing an attacker's
        # undeliverable hashes to an arbitrary honest peer would let the
        # stall detector cascade-evict peers that never had them. Entries
        # are created only for accepted headers (PoW-gated) and dropped
        # on delivery or when the last announcer disconnects.
        self._block_sources: dict[bytes, set[int]] = {}
        self._nonce = secrets.randbits(64)  # self-connect detection
        # -- tunables for the supervision machinery. Reads go through
        # node.net_limits when the node built one (node/node.py), with the
        # same defaults otherwise so bare test stubs work.
        limits = getattr(node, "net_limits", None) or {}
        # DISCOURAGEMENT_THRESHOLD: ban_score at/above this evicts
        self.ban_threshold = int(limits.get("banscore", 100))
        # BLOCK_DOWNLOAD_TIMEOUT analogue (seconds without download
        # progress before a peer with blocks in flight counts as stalling)
        self.block_download_timeout = float(
            limits.get("blockdownloadtimeout", 60))
        # supervision tick cadence (_tick) — pings, stall checks, expiry
        self.tick_interval = float(limits.get("nettick", 5))
        # per-peer receive ceiling, bytes/sec averaged over one tick
        # window; 0 disables
        self.max_recv_rate = int(limits.get("maxrecvrate", 4_000_000))
        # charge every Nth non-connecting headers message (see
        # MAX_UNCONNECTING_HEADERS above)
        self.max_unconnecting = max(1, int(
            limits.get("maxunconnectingheaders", MAX_UNCONNECTING_HEADERS)))
        # when the last supervision tick actually ran (None before the
        # first): the receive-rate window divides by REAL elapsed time so
        # a delayed tick doesn't inflate honest peers' measured rates
        self._last_tick: Optional[float] = None
        # seed for the orphan-eviction rng: deterministic when set (tests/
        # chaos campaigns), OS entropy when -1 (production default — a
        # predictable eviction order is itself an attack surface)
        seed = int(limits.get("netseed", -1))
        self._rng = random.Random(seed if seed >= 0 else None)
        # aggregate supervision counters (gettpuinfo "net" section)
        self.net_stats = {
            "misbehavior_charges": 0,   # individual charges applied
            "discharged_peers": 0,      # peers evicted at the threshold
            "stall_rerequests": 0,      # blocks re-requested off a STALLER
            "disconnect_rerequests": 0,  # moved off an ordinary disconnect
            "parked_handoffs": 0,       # parked blocks handed out by _tick
            "evicted_stallers": 0,
            "flood_charges": 0,         # recv-rate ceiling violations
            "orphans_evicted": 0,       # random evictions at the budget
            "net_faults_injected": 0,   # BCP_FAULT_OPS=net drops
            "backfill_retries": 0,      # backfill deadlines that fired
            "backfill_peer_evictions": 0,  # peers struck from backfill
        }
        # assumeutxo backfill supervision: the shadow-validation thread's
        # history pull must never wedge behind one dead/stalling peer for
        # a full blockdownloadtimeout — every backfill hash carries its
        # own (shorter) deadline; on expiry the hash is torn off its
        # owner and re-requested from the NEXT peer after a jittered
        # Backoff pause, and a peer that repeatedly eats backfill
        # requests is struck out of the backfill rotation (it still
        # serves normal announcements — the strike-out is scoped to the
        # pull the peer demonstrably can't serve).
        self.backfill_timeout = float(limits.get(
            "backfilltimeout", min(10.0, self.block_download_timeout)))
        # hash -> {"peer": owner id, "deadline": abs time, "boff":
        #          per-hash Backoff, "retry_at": pause gate (0 = none)}
        self._backfill: dict[bytes, dict] = {}
        self._backfill_strikes: dict[int, int] = {}
        self._backfill_evicted: set[int] = set()
        self.discharge_reasons: dict[str, int] = {}  # reason -> evictions
        # CConnman/BanMan (src/banman.cpp): ip -> ban-expiry unix time.
        # Host granularity (no CIDR) matching how we track peers. Persisted
        # across restarts via banlist.json (banman.cpp LoadBanlist).
        self._banlist_path = os.path.join(node.datadir, "banlist.json")
        # _ban_lock guards the in-memory dict only (is_banned runs on the
        # event loop for every accept/dial — it must never wait on disk);
        # mutators snapshot under it and persist OUTSIDE it, serialized
        # by _ban_io_lock with a sequence check so an older snapshot can
        # never overwrite a newer one (atomic_write_bytes renames a fixed
        # path + ".tmp", so concurrent writers must not interleave)
        self._ban_lock = lockwatch.watched_lock("ban_lock")
        self._ban_io_lock = lockwatch.watched_lock("ban_io_lock")
        # publish the static GUARDED_BY vocabulary to the runtime
        # sentinel so gettpuinfo.lockwatch and docs/CONCURRENCY.md agree
        for field, lk in self.GUARDED_BY.items():
            lockwatch.declare_guards(lk, [field])
        self._ban_seq = 0        # bumped under _ban_lock per mutation
        self._ban_saved_seq = 0  # last seq persisted (under _ban_io_lock)
        self._banned: dict[str, float] = self._load_banlist()
        # telemetry: tick-duration histogram (inline in _tick) plus a
        # scrape-time collector projecting net_stats and per-peer recv
        # rates into the registry — live state, no stale labeled gauges
        # for long-gone peers. Re-registering replaces any previous
        # connman's collector (one live P2P stack per process).
        tm.register_collector("net", self._telemetry_families)
        self.bantime = 86400  # -bantime default
        # mapOrphanTransactions (net_processing.cpp): txs whose inputs we
        # don't know yet. Bounded by count AND bytes; over-budget inserts
        # evict a seeded-random victim (LimitOrphanTxSize), and a peer's
        # orphans are erased when it disconnects (per-peer attribution).
        # txid -> (tx, source peer id, serialized size, parked-at time)
        self._orphans: dict[bytes, tuple[CTransaction, int, int, float]] = {}
        self._orphan_bytes = 0
        # -addnode / addnode RPC "add" targets (vAddedNodes, net.cpp)
        self.added_nodes: list[str] = []
        # mapRelay (net_processing.cpp): recently relayed txs kept
        # RELAY_TX_CACHE_TIME so getdata can be served after the tx leaves
        # the mempool (e.g. it was just mined)
        self._relay_memory: dict[bytes, tuple[CTransaction, float]] = {}
        # CAddrMan + peers.dat (src/addrman.cpp, net.cpp DumpAddresses)
        from .addrman import AddrMan

        self.addrman = AddrMan()
        self._peers_path = os.path.join(node.datadir, "peers.json")
        n_loaded = self.addrman.load(self._peers_path)
        if n_loaded:
            log_print("net", "loaded %d addresses from peers.json", n_loaded)
        # -maxconnections (net.cpp nMaxConnections, default 125): inbound
        # accepts are refused at the cap
        self.max_connections = node.config.get_int("maxconnections", 125)
        # ThreadOpenConnections target, clamped by the total cap exactly
        # like the reference's min(MAX_OUTBOUND_CONNECTIONS, nMaxConnections)
        self.max_outbound = min(8, self.max_connections)
        # reconnect pacing (util/faults.Backoff): repeated dial failures
        # back the open-connections loop off exponentially with jitter
        # (instead of the old fixed 5 s poll hammering a dead candidate
        # list); any completed handshake resets it to the base interval
        self._dial_backoff = Backoff(base=5.0, factor=2.0, maximum=60.0,
                                     jitter=0.5)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="p2p", daemon=True)
        self._thread.start()
        if not self._started.wait(30):
            raise RuntimeError("P2P event loop failed to start")
        self.node.chainstate.on_tip_changed.append(self._on_tip_changed)

    def _run(self) -> None:
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)
        if self.listen_port:  # 0 = -listen=0 (outbound only)
            self.loop.run_until_complete(self._start_server())
        self.loop.create_task(self._tick_loop())
        self.loop.create_task(self._open_connections_loop())
        self._started.set()
        self.loop.run_forever()
        # drain: close transports
        for task in asyncio.all_tasks(self.loop):
            task.cancel()
        self.loop.run_until_complete(asyncio.sleep(0))
        self.loop.close()

    async def _tick_loop(self) -> None:
        """Drive _tick on a fixed cadence. The cadence (self.tick_interval)
        bounds how fast stalls, floods, and inactivity are noticed — it is
        deliberately much shorter than PING_INTERVAL; _tick itself paces
        pings by wall clock."""
        while True:
            await asyncio.sleep(self.tick_interval)
            try:
                self._tick(time.time())
            except Exception as e:  # the supervisor itself must not die
                log_printf("P2P tick error: %r", e)

    def _tick(self, now: float) -> None:
        """One supervision pass (InactivityCheck + PingPeriodicity of
        net.cpp:~1300 plus this framework's stall/flood/expiry sweeps).
        Takes the clock as an argument so tests drive it directly with a
        fake ``now`` — no sleeping, no event loop required.

        Per tick: expire mapRelay and aged orphans; then per peer — drop
        on inactivity, ping on cadence, close the receive-rate window
        (charging floods), and run block-download stall detection
        (re-request from another peer, then evict the staller)."""
        t_tick = time.monotonic()
        # speculation-tree stale sweep: a tip held inside the -spechold
        # grace (or a fork-race tie) must still externalize when no
        # further block ever arrives — re-run the live settle policy
        # each tick so getbestblockhash/listeners lag a quiet tip by at
        # most hold + one supervision pass (ties by 10x the hold)
        cs = getattr(self.node, "chainstate", None)
        if cs is not None and getattr(cs, "_spec", None):
            with self.node.cs_main:
                cs.settle_live()
        # rate windows are normalized by the time since the previous tick
        # actually ran — a tick delayed by a long validation must not
        # read the drained backlog as a flood
        if self._last_tick is None or now <= self._last_tick:
            elapsed = self.tick_interval
        else:
            elapsed = now - self._last_tick
        self._last_tick = now
        # expire mapRelay entries in place — RPC threads insert into
        # this dict concurrently, so never rebind it
        for h, v in list(self._relay_memory.items()):
            if v[1] <= now:
                # benign cache race: snapshot iteration and pop(h, None)
                # are each GIL-atomic; a racing RPC re-insert that loses
                # its entry to this expiry sweep just re-relays later
                self._relay_memory.pop(h, None)  # BCPLINT-IGNORE[BCP008]: benign GIL-atomic cache expiry race
        # expire aged orphans (ORPHAN_TX_EXPIRE_TIME)
        for txid, entry in list(self._orphans.items()):
            if entry[3] + ORPHAN_EXPIRE_TIME <= now:
                self._remove_orphan(txid)
        for peer in list(self.peers.values()):
            quiet = now - max(peer.last_recv, peer.connected_at)
            if quiet > TIMEOUT_INTERVAL:
                log_print("net", "peer=%d inactivity timeout — dropping",
                          peer.id)
                peer.writer.close()
                continue
            if peer.handshaked and now - peer.last_ping_sent >= PING_INTERVAL:
                peer.last_ping_sent = now
                try:
                    peer.send("ping", ser_ping(secrets.randbits(64)))
                except Exception:
                    pass
            # close this tick's receive window and charge floods
            window, peer.recv_window = peer.recv_window, 0
            peer.recv_rate = window / max(elapsed, 1e-9)
            if (self.max_recv_rate and not peer.discharged
                    and peer.recv_rate > self.max_recv_rate):
                peer.flood_strikes += 1
                self.net_stats["flood_charges"] += 1
                self.misbehaving(peer, CHARGE_RECV_FLOOD, "recv-flood")
            self._check_stall(peer, now)
        # backfill deadline sweep (assumeutxo history pull supervision)
        if self._backfill:
            self._tick_backfill(now)
        # blocks orphaned by a stalled/vanished owner with no available
        # announcer at the time: hand them to an announcer that freed up
        # (hashes whose announcers are all gone are dropped inside)
        if self._unrequested:
            hashes = list(self._unrequested)
            self._unrequested.clear()
            self.net_stats["parked_handoffs"] += \
                self._dispatch_wanted(hashes, now=now)
        # wall clock, not the caller's fake `now`: the histogram measures
        # how long the tick occupied the event loop
        _TICK_H.observe(time.monotonic() - t_tick)

    def _check_stall(self, peer: Peer, now: float) -> None:
        """Block-download stall detection (net_processing.cpp's
        BLOCK_DOWNLOAD_TIMEOUT / BLOCK_STALLING_TIMEOUT pair, collapsed to
        per-peer progress tracking): a peer with blocks in flight and no
        download progress for block_download_timeout seconds is marked
        stalling, charged half the discharge threshold (visible in
        getpeerinfo), and its in-flight blocks are re-requested from
        another peer; a further timeout without redemption discharges it.
        Receiving any requested block clears the stalling mark."""
        if peer.discharged:
            return
        if peer.stalling:
            if now - peer.stalling_since > self.block_download_timeout:
                self.net_stats["evicted_stallers"] += 1
                self.misbehaving(peer, self.ban_threshold, "stalled-block")
            return
        if peer.inflight and \
                now - peer.last_block_progress > self.block_download_timeout:
            peer.stalling = True
            peer.stalling_since = now
            log_print("net", "peer=%d stalling: %d blocks in flight, no "
                      "progress for %.0fs", peer.id, len(peer.inflight),
                      now - peer.last_block_progress)
            # provisional: rolled back if the peer redeems itself by
            # delivering a still-wanted block before the fallback peer
            # does (an honest slow link must not carry the charge forever
            # — two redeemed episodes would otherwise add up to an
            # instant eviction on the second, with no timeout at all).
            # When a faster peer wins the re-request race there is
            # nothing left to redeem with and the second timeout evicts —
            # deliberately still gentler than the reference, which
            # disconnects stallers after BLOCK_STALLING_TIMEOUT (2 s)
            # with no redemption window at all.
            charge = max(1, self.ban_threshold // 2)
            peer.stall_charge = charge
            self.misbehaving(peer, charge, "stalled-block")
            self._reassign_inflight(peer, now, stalled=True)

    # -- misbehavior ledger (net_processing.cpp Misbehaving) ------------

    # caps on the reason-keyed ledger dicts: reason strings can embed
    # attacker-chosen values (e.g. "oversized payload <N>"), so both the
    # key length and the number of distinct keys are bounded — overflow
    # buckets into "other" instead of growing without limit
    MAX_REASON_LEN = 48
    MAX_REASON_KEYS = 64

    @classmethod
    def _reason_key(cls, reason: str, existing: dict) -> str:
        key = reason[:cls.MAX_REASON_LEN]
        if key in existing or len(existing) < cls.MAX_REASON_KEYS:
            return key
        return "other"

    def misbehaving(self, peer: Peer, score: int, reason: str) -> None:
        """Charge ``score`` to the peer's ban-score ledger; at or above
        the discharge threshold the peer is evicted (connection closed).
        Banning stays operator-driven (setban) — everything dials loopback
        here, and auto-banning 127.0.0.1 would take out every future peer
        on the host."""
        peer.ban_score += score
        key = self._reason_key(reason, peer.charges)
        peer.charges[key] = peer.charges.get(key, 0) + score
        self.net_stats["misbehavior_charges"] += 1
        log_print("net", "peer=%d misbehaving (+%d => %d): %s",
                  peer.id, score, peer.ban_score, reason)
        if peer.ban_score >= self.ban_threshold and not peer.discharged:
            peer.discharged = True
            self.net_stats["discharged_peers"] += 1
            key = self._reason_key(reason, self.discharge_reasons)
            self.discharge_reasons[key] = \
                self.discharge_reasons.get(key, 0) + 1
            log_print("net", "peer=%d discharged at %d (threshold %d) — "
                      "evicting", peer.id, peer.ban_score, self.ban_threshold)
            try:
                peer.writer.close()
            except Exception:
                pass

    # -- block-download bookkeeping -------------------------------------

    def _request_blocks(self, peer: Peer, hashes: list[bytes],
                        now: Optional[float] = None) -> int:
        """Send one getdata for every hash not already in flight and
        account it against the peer (vBlocksInFlight). Returns how many
        hashes were actually requested — callers tallying re-request
        counters must not count the already-in-flight ones."""
        if now is None:
            now = time.time()
        fresh = [h for h in hashes if h not in self._requested_blocks]
        if not fresh:
            return 0
        # start the stall clock only when the peer goes from idle to owing
        # blocks — while it already owes, only an actual ARRIVAL refreshes
        # the clock (_note_block_arrival). Refreshing on every send would
        # let a peer trickle one new header per timeout window and hold
        # its growing in-flight set hostage forever.
        if not peer.inflight:
            peer.last_block_progress = now
        for h in fresh:
            self._requested_blocks[h] = peer.id
            peer.inflight.add(h)
            self._unrequested.discard(h)
            # every getdata target is an announcer of the hash — keeps
            # the sources invariant for re-request routing
            self._block_sources.setdefault(h, set()).add(peer.id)
        try:
            peer.send("getdata", ser_inv([(MSG_BLOCK, h) for h in fresh]))
        except Exception:
            pass
        return len(fresh)

    def _request_or_park(self, peer: Peer, hashes: list[bytes]) -> None:
        """getdata the hashes from ``peer`` unless it is already marked
        stalling or discharged — a known-bad peer must never re-reserve a
        download against itself (the stall-and-reannounce cycle buys an
        extra timeout of sync delay per round). Parked hashes carry the
        peer as an announcer so _tick can route them once it redeems (or
        to any other announcer)."""
        if peer.stalling or peer.discharged:
            for h in hashes:
                self._block_sources.setdefault(h, set()).add(peer.id)
            self._unrequested.update(hashes)
        else:
            self._request_blocks(peer, hashes)

    # consecutive backfill deadline misses before a peer is struck out
    # of the backfill rotation (redeemed by delivering any wanted block)
    BACKFILL_EVICT_STRIKES = 3

    def request_backfill(self, hashes: list[bytes]) -> None:
        """Pull specific historical blocks (assumeutxo background sync).

        Header sync can't drive this download: the snapshot node's locator
        already contains the snapshot tip, so peers announce nothing below
        it — the verify thread names the heights it is missing instead.
        Thread-safe (called from the snapshot-verify thread); chunks are
        spread round-robin across live peers and from there inherit all of
        the normal in-flight dedupe, stall detection and re-request
        routing — plus a per-hash backfill deadline (backfilltimeout,
        much shorter than the stall window) so a dead peer can't wedge
        the shadow-validation thread: _tick_backfill retries elsewhere."""
        if not hashes:
            return
        wanted = list(hashes)

        def _go() -> None:
            self._backfill_dispatch(wanted, time.time())

        if self.loop is None:
            _go()  # unit tests drive connman with no event loop
        else:
            self.loop.call_soon_threadsafe(_go)

    def _backfill_peers(self, exclude: int = -1) -> list[Peer]:
        """Peers eligible to serve a backfill pull. Struck-out peers are
        skipped while any alternative exists; when every live peer is
        struck out they are used anyway — a degraded pull beats a wedged
        one, and a delivery un-strikes the peer."""
        live = [p for p in self.peers.values()
                if p.handshaked and not p.stalling and not p.discharged
                and p.id != exclude]
        fresh = [p for p in live if p.id not in self._backfill_evicted]
        return fresh if fresh else live

    def _backfill_dispatch(self, wanted: list[bytes], now: float) -> None:
        boff = lambda: Backoff(base=0.25, factor=2.0, maximum=5.0,  # noqa: E731
                               rng=self._rng)
        for h in wanted:
            self._backfill.setdefault(h, {
                "peer": -1, "deadline": now + self.backfill_timeout,
                "boff": boff(), "retry_at": 0.0,
            })
        peers = self._backfill_peers()
        if not peers:
            # no usable peer yet — park them; every future announcer
            # (or redeemed staller) picks them up via _tick
            self._unrequested.update(wanted)
            return
        for i, peer in enumerate(peers):
            chunk = [h for h in wanted[i::len(peers)]
                     if h not in self._requested_blocks]
            if chunk:
                self._request_blocks(peer, chunk, now=now)
                for h in chunk:
                    self._backfill[h]["peer"] = peer.id

    def _tick_backfill(self, now: float) -> None:
        """Per-tick backfill deadline sweep: expire overdue pulls, strike
        their owners, and re-request each hash from the next eligible
        peer after a jittered Backoff pause (the pause keeps a flapping
        peer set from being hammered in lockstep)."""
        for h, entry in list(self._backfill.items()):
            owner_id = self._requested_blocks.get(h)
            if owner_id is None and h not in self._unrequested \
                    and not entry["retry_at"]:
                # delivered (or dropped) through the normal path — retire
                self._backfill.pop(h, None)
                continue
            if entry["retry_at"]:
                if now >= entry["retry_at"]:
                    entry["retry_at"] = 0.0
                    self._backfill_retry(h, entry, now)
                continue
            if now < entry["deadline"]:
                continue
            # deadline fired: tear the hash off its owner and schedule
            # the retry; the owner is struck (evicted from the backfill
            # rotation at BACKFILL_EVICT_STRIKES)
            self.net_stats["backfill_retries"] += 1
            if owner_id is not None:
                owner = self.peers.get(owner_id)
                if owner is not None:
                    owner.inflight.discard(h)
                strikes = self._backfill_strikes.get(owner_id, 0) + 1
                self._backfill_strikes[owner_id] = strikes
                if strikes >= self.BACKFILL_EVICT_STRIKES \
                        and owner_id not in self._backfill_evicted:
                    self._backfill_evicted.add(owner_id)
                    self.net_stats["backfill_peer_evictions"] += 1
                    log_print("net", "peer=%d struck out of backfill "
                              "rotation (%d missed deadlines)",
                              owner_id, strikes)
                entry["peer"] = owner_id
            self._requested_blocks.pop(h, None)
            self._unrequested.discard(h)
            entry["retry_at"] = now + entry["boff"].next()

    def cancel_backfill(self) -> None:
        """Abandon every outstanding backfill pull (ISSUE 17): the shadow
        validator hard-aborted (epoch-digest divergence, rejected block or
        final digest mismatch), so the history it was naming is for a
        chainstate that will never be promoted — keeping the requests
        alive would waste peer goodput and hold getdata reservations on a
        node that is about to shut down for manual intervention.
        Thread-safe like request_backfill."""

        def _go() -> None:
            for h in list(self._backfill):
                owner_id = self._requested_blocks.pop(h, None)
                if owner_id is not None:
                    owner = self.peers.get(owner_id)
                    if owner is not None:
                        owner.inflight.discard(h)
                self._unrequested.discard(h)
            n = len(self._backfill)
            self._backfill.clear()
            if n:
                log_print("net", "backfill cancelled: %d outstanding "
                          "pull(s) abandoned", n)

        if self.loop is None:
            _go()
        else:
            self.loop.call_soon_threadsafe(_go)

    def _backfill_retry(self, h: bytes, entry: dict, now: float) -> None:
        peers = self._backfill_peers(exclude=entry["peer"])
        if not peers:
            self._unrequested.add(h)  # parked; _tick hands it out later
            return
        peer = peers[self._rng.randrange(len(peers))]
        if self._request_blocks(peer, [h], now=now):
            entry["peer"] = peer.id
            entry["deadline"] = now + self.backfill_timeout

    def _note_block_arrival(self, peer: Peer, h: bytes,
                            wire_bytes: int = 0,
                            now: Optional[float] = None) -> None:
        """A block landed (full, compact, or reconstructed): clear the
        in-flight entry. Only a block the peer actually OWED counts as
        download progress / stall redemption — an unsolicited push (e.g.
        replaying a block we already have, like genesis) must not refresh
        the stall clock, or a withholding peer could keep its reserved
        getdata hashes hostage forever by feeding duplicates. The hash may
        be charged to a DIFFERENT peer (a reassigned download whose
        original owner finally delivered): clear the recorded owner's
        in-flight entry too, or that owner would be falsely marked
        stalling over a block we already have."""
        if self._backfill.pop(h, None) is not None:
            # a delivered backfill block redeems the deliverer's strikes
            # and re-admits it to the backfill rotation
            self._backfill_strikes.pop(peer.id, None)
            self._backfill_evicted.discard(peer.id)
        owner_id = self._requested_blocks.pop(h, None)
        parked = h in self._unrequested
        self._unrequested.discard(h)
        # _block_sources is NOT dropped here: arrival precedes validation,
        # and a poisoned delivery (garbage body under a wanted header)
        # re-parks the hash — the surviving announcers are where the
        # re-request goes. _process_block_obj drops the entry once the
        # block really lands.
        # progress = delivering a block the node actually WANTED (in
        # flight with anyone, or parked awaiting a peer) — a replayed
        # known block scores nothing
        useful = owner_id is not None or parked or h in peer.inflight
        peer.inflight.discard(h)
        if owner_id is not None and owner_id != peer.id:
            owner = self.peers.get(owner_id)
            if owner is not None:
                owner.inflight.discard(h)
        if useful:
            # solicited download traffic is exempt from the flood ceiling
            # — we asked for these bytes, and an honest peer serving our
            # getdata at wire speed must never be charged for it
            if wire_bytes:
                peer.recv_window = max(0, peer.recv_window - wire_bytes)
            peer.last_block_progress = time.time() if now is None else now
            if peer.stalling:
                peer.stalling = False  # redeemed before the final timeout
                # roll the provisional charge back off the ledger: the
                # contract is "a further timeout WITHOUT redemption
                # discharges" — a redeemed episode must not leave the
                # peer one slow block away from instant eviction
                if peer.stall_charge and not peer.discharged:
                    peer.ban_score = max(
                        0, peer.ban_score - peer.stall_charge)
                    left = peer.charges.get("stalled-block", 0) \
                        - peer.stall_charge
                    if left > 0:
                        peer.charges["stalled-block"] = left
                    else:
                        peer.charges.pop("stalled-block", None)
                peer.stall_charge = 0

    def _reassign_inflight(self, loser: Peer, now: Optional[float] = None,
                           stalled: bool = False) -> None:
        """Move every block the peer still owes onto other ANNOUNCERS of
        those blocks (via _dispatch_wanted); hashes whose announcers are
        all busy are parked for _tick, hashes nobody else ever announced
        are dropped. ``stalled`` keys which counter the move lands in —
        gettpuinfo's stall_rerequests must reflect actual stall evictions,
        not benign peer churn, or operator dashboards read ordinary
        disconnects as an attack."""
        hashes = [h for h, pid in self._requested_blocks.items()
                  if pid == loser.id]
        loser.inflight.clear()
        for h in hashes:
            self._requested_blocks.pop(h, None)
        if not hashes:
            return
        moved = self._dispatch_wanted(hashes, exclude=loser.id, now=now)
        if moved:
            counter = ("stall_rerequests" if stalled
                       else "disconnect_rerequests")
            self.net_stats[counter] += moved
            log_print("net", "re-requested %d of %d blocks owed by "
                      "peer=%d%s", moved, len(hashes), loser.id,
                      ", stalled" if stalled else "")

    def _dispatch_wanted(self, hashes: list[bytes],
                         exclude: Optional[int] = None,
                         now: Optional[float] = None) -> int:
        """Route wanted block hashes to live peers that ANNOUNCED them —
        the only peers it is fair to hold accountable for delivery.
        Requesting from a non-announcer and then stall-charging it would
        let one attacker's undeliverable announcements cascade-evict
        every honest peer. Per hash: request from the least-loaded
        available announcer; park (``_unrequested``) while every announcer
        is busy; forget the download once no announcer is connected at
        all — if the block matters, a future headers/cmpctblock
        announcement from a peer that has it starts it over. Returns the
        number of hashes actually re-requested."""
        by_target: dict[int, list[bytes]] = {}
        for h in hashes:
            if h in self._requested_blocks:
                continue  # already in flight with another owner
            src = self._block_sources.get(h)
            if src is not None:
                src.intersection_update(self.peers)  # prune dead peers
            if not src:
                self._block_sources.pop(h, None)
                self._unrequested.discard(h)
                log_print("net", "dropping block %s — no announcer left",
                          hash_to_hex(h)[:16])
                continue
            candidates = [
                self.peers[pid] for pid in src
                if pid != exclude and self.peers[pid].handshaked
                and not self.peers[pid].discharged
                and not self.peers[pid].stalling
            ]
            if not candidates:
                self._unrequested.add(h)  # until an announcer frees up
                continue
            target = min(candidates, key=lambda p: len(p.inflight))
            by_target.setdefault(target.id, []).append(h)
        moved = 0
        for pid, hs in by_target.items():
            # count only what actually went out — a hash already in
            # flight elsewhere is filtered inside, and counting it would
            # inflate the operator-facing re-request counters
            moved += self._request_blocks(self.peers[pid], hs, now)
        return moved

    # -- orphan pool (mapOrphanTransactions) ----------------------------

    def _add_orphan(self, peer: Optional[Peer], tx: CTransaction) -> None:
        size = len(tx.serialize())
        if size > MAX_ORPHAN_TX_SIZE:
            log_print("net", "ignoring oversized orphan %s (%d bytes)",
                      tx.txid_hex[:16], size)
            return
        if tx.txid in self._orphans:
            return
        self._orphans[tx.txid] = (
            tx, peer.id if peer is not None else 0, size, time.time())
        self._orphan_bytes += size
        # LimitOrphanTxSize: evict seeded-random victims until both the
        # count cap and the byte budget hold
        while (len(self._orphans) > MAX_ORPHAN_TX
               or self._orphan_bytes > MAX_ORPHAN_BYTES):
            victim = self._rng.choice(list(self._orphans))
            self._remove_orphan(victim)
            self.net_stats["orphans_evicted"] += 1
        log_print("net", "orphan tx %s parked (%d pooled, %d bytes)",
                  tx.txid_hex[:16], len(self._orphans), self._orphan_bytes)

    def _remove_orphan(self, txid: bytes) -> None:
        entry = self._orphans.pop(txid, None)
        if entry is not None:
            self._orphan_bytes -= entry[2]

    def _erase_sources_for(self, peer_id: int) -> None:
        """Drop a disconnected peer from every announcement-source set;
        a hash with no announcer left that isn't actively tracked is
        forgotten entirely (this keeps the documented invariant that
        entries die with their last announcer — a pending-cmpctblock
        hash, for example, has no other pruning site)."""
        for h in list(self._block_sources):
            src = self._block_sources[h]
            src.discard(peer_id)
            if not src and h not in self._requested_blocks:
                self._block_sources.pop(h, None)
                self._unrequested.discard(h)

    def _erase_orphans_for(self, peer_id: int) -> None:
        """EraseOrphansFor: a disconnected peer's parked orphans go with it
        (per-peer attribution keeps one peer from squatting the pool)."""
        mine = [txid for txid, e in self._orphans.items() if e[1] == peer_id]
        for txid in mine:
            self._remove_orphan(txid)
        if mine:
            log_print("net", "erased %d orphans from peer=%d",
                      len(mine), peer_id)

    def _telemetry_families(self) -> list:
        """Registry collector: net_stats counters, pool/banlist gauges,
        and per-peer receive-rate gauges (live peers only — labels die
        with their peer, unlike a mutable labeled gauge would)."""
        out = tm.flat_families("bcp_net", self.net_stats, typ="counter",
                              help="p2p/connman supervision counters")
        out.append({"name": "bcp_net_peers", "type": "gauge",
                    "help": "Connected peers",
                    "samples": [({}, len(self.peers))]})
        out.append({"name": "bcp_net_orphans", "type": "gauge",
                    "help": "Parked orphan transactions",
                    "samples": [({}, len(self._orphans))]})
        out.append({"name": "bcp_net_banned", "type": "gauge",
                    "help": "Banlist entries",
                    "samples": [({}, len(self._banned))]})
        peers = list(self.peers.values())
        if peers:
            out.append({
                "name": "bcp_peer_recv_rate_bytes", "type": "gauge",
                "help": "Per-peer receive rate over the last tick window "
                        "(bytes/sec)",
                "samples": [({"peer": str(p.id)}, round(p.recv_rate, 1))
                            for p in peers],
            })
        return out

    def net_snapshot(self) -> dict:
        """gettpuinfo 'net' section: the supervision counters an operator
        needs to see why peers are being charged and evicted."""
        return {
            **self.net_stats,
            "discharge_reasons": dict(self.discharge_reasons),
            "orphans": {"count": len(self._orphans),
                        "bytes": self._orphan_bytes},
            "requested_blocks": len(self._requested_blocks),
            "unrequested_blocks": len(self._unrequested),
            "banned": len(self._banned),
            "ban_threshold": self.ban_threshold,
            "block_download_timeout": self.block_download_timeout,
            "max_recv_rate": self.max_recv_rate,
        }

    async def _start_server(self) -> None:
        self._server = await asyncio.start_server(
            self._on_inbound, self.bind_host, self.listen_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log_print("net", "P2P listening on %s:%d", self.bind_host, self.port)

    def close(self) -> None:
        # the 'net' collector holds a bound method of this connman; a
        # closed P2P stack must not stay reachable through the registry
        tm.REGISTRY.unregister_collector("net")
        if self.loop is None:
            return

        def _shutdown():
            for peer in list(self.peers.values()):
                peer.writer.close()
            if self._server is not None:
                self._server.close()
            self.loop.stop()

        self.loop.call_soon_threadsafe(_shutdown)
        self._thread.join(10)
        try:
            self.addrman.save(self._peers_path)  # DumpAddresses
        except OSError as e:
            log_printf("peers.json save failed: %r", e)

    # -- dialing --------------------------------------------------------

    def connect_to(self, host: str, port: int) -> None:
        asyncio.run_coroutine_threadsafe(self._dial(host, port), self.loop)

    async def _dial(self, host: str, port: int) -> None:
        try:
            reader, writer = await asyncio.open_connection(host, port)
        except OSError as e:
            log_print("net", "connect to %s:%d failed: %s", host, port, e)
            return
        peer = Peer(self, reader, writer, outbound=True)
        self.peers[peer.id] = peer
        peer.send("version", self._version_payload().serialize())
        asyncio.ensure_future(self._peer_loop(peer))

    async def _on_inbound(self, reader, writer) -> None:
        peername = writer.get_extra_info("peername") or ("?", 0)
        if self.is_banned(peername[0]) or \
                len(self.peers) >= self.max_connections:
            writer.close()
            return
        peer = Peer(self, reader, writer, outbound=False)
        self.peers[peer.id] = peer
        await self._peer_loop(peer)

    # -- ban list (src/banman.cpp) --------------------------------------

    def _load_banlist(self) -> dict[str, float]:
        """LoadBanlist: read banlist.json, pruning entries that expired
        while the node was down (SweepBanned)."""
        raw = read_json(self._banlist_path, default=None)
        if not isinstance(raw, dict):
            return {}
        now = time.time()
        try:
            banned = {
                str(ip): float(until)
                for ip, until in raw.get("banned", {}).items()
                if float(until) > now
            }
        except (AttributeError, TypeError, ValueError):
            # structurally wrong sidecar (hand-edited, torn writer):
            # startup must never die on it — log and start clean
            log_printf("banlist.json malformed — ignoring")
            return {}
        if banned:
            log_print("net", "loaded %d banned hosts from banlist.json",
                      len(banned))
        return banned

    def _snapshot_banlist(self) -> tuple[int, dict[str, float]]:
        """Caller holds _ban_lock: bump the mutation sequence and copy the
        dict for persisting after the lock is released."""
        self._ban_seq += 1
        return self._ban_seq, dict(self._banned)

    def _persist_banlist(self, seq: int, snap: dict[str, float]) -> None:
        """DumpBanlist: every mutation (setban add/remove, clearbanned)
        writes through so a crash never loses an operator's ban. Runs
        WITHOUT _ban_lock — the fsync must not stall the event loop's
        is_banned checks; _ban_io_lock serializes writers and the seq
        check drops a snapshot that lost the race to a newer one."""
        with self._ban_io_lock:
            if seq <= self._ban_saved_seq:
                return  # a newer snapshot already reached the disk
            self._ban_saved_seq = seq
            try:
                atomic_write_json(self._banlist_path,
                                  {"version": 1, "banned": snap})
            except OSError as e:
                log_printf("banlist.json save failed: %r", e)

    def is_banned(self, ip: str) -> bool:
        with self._ban_lock:
            until = self._banned.get(ip)
            if until is None:
                return False
            if time.time() > until:
                self._banned.pop(ip, None)
                return False
            return True

    def ban(self, ip: str, bantime: int = 0) -> None:
        with self._ban_lock:
            self._banned[ip] = time.time() + (bantime or self.bantime)
            seq, snap = self._snapshot_banlist()
        self._persist_banlist(seq, snap)
        # drop any live connections from that host
        def _do():
            for peer in list(self.peers.values()):
                if peer.addr.rsplit(":", 1)[0] == ip:
                    peer.writer.close()
        if self.loop is not None:
            self.loop.call_soon_threadsafe(_do)

    def unban(self, ip: str) -> bool:
        with self._ban_lock:
            hit = self._banned.pop(ip, None) is not None
            if not hit:
                return False
            seq, snap = self._snapshot_banlist()
        self._persist_banlist(seq, snap)
        return True

    def banned(self) -> dict[str, float]:
        now = time.time()
        # prune + snapshot under the lock: an unlocked rebind here would
        # drop a ban a concurrent setban just inserted (lost update that
        # the next locked mutation would then persist to disk)
        with self._ban_lock:
            self._banned = {ip: t for ip, t in self._banned.items()
                            if t > now}
            return dict(self._banned)

    def clear_banned(self) -> None:
        with self._ban_lock:
            self._banned.clear()
            seq, snap = self._snapshot_banlist()
        self._persist_banlist(seq, snap)

    def ping_all(self) -> None:
        def _do():
            for peer in self.peers.values():
                if peer.handshaked:
                    try:
                        peer.send("ping", ser_ping(secrets.randbits(64)))
                    except Exception:
                        pass
        if self.loop is not None:
            self.loop.call_soon_threadsafe(_do)

    def disconnect(self, addr: str) -> None:
        def _do():
            for peer in list(self.peers.values()):
                if peer.addr == addr:
                    peer.writer.close()
        self.loop.call_soon_threadsafe(_do)

    def _version_payload(self) -> VersionPayload:
        with self.node.cs_main:
            height = self.node.chainstate.tip().height
        return VersionPayload(nonce=self._nonce, start_height=height)

    # -- per-peer receive loop -----------------------------------------

    async def _peer_loop(self, peer: Peer) -> None:
        try:
            while True:
                raw_header = await peer.reader.readexactly(HEADER_SIZE)
                header = MessageHeader.parse(raw_header, self.magic)
                payload = await peer.reader.readexactly(header.length)
                check_payload(header, payload)
                peer.bytes_recv += HEADER_SIZE + header.length
                self.bytes_recv += HEADER_SIZE + header.length
                peer.recv_window += HEADER_SIZE + header.length
                peer.last_recv = time.time()
                await self._process_message(peer, header.command, payload)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer hung up
        except NetMessageError as e:
            # the raise ends the connection regardless; the charge still
            # goes through the ledger so counters/reasons are recorded
            # (an un-annotated NetMessageError scores 100 = immediate
            # discharge, the historical behavior). score=0 marks benign
            # protocol disconnects (self-connect, duplicate version) that
            # must not pollute the attack counters.
            score = getattr(e, "score", 100)
            if score > 0:
                self.misbehaving(peer, score, str(e))
            else:
                log_print("net", "peer=%d disconnecting: %s", peer.id, e)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log_printf("P2P internal error peer=%d: %r", peer.id, e)
        finally:
            self.peers.pop(peer.id, None)
            # per-peer attribution: its parked orphans go with it, and its
            # in-flight block requests move to another peer (or are freed
            # for re-announcement) so sync keeps making progress
            self._erase_orphans_for(peer.id)
            self._reassign_inflight(peer)
            self._erase_sources_for(peer.id)
            # peer ids are never reused — drop its backfill ledger rows
            self._backfill_strikes.pop(peer.id, None)
            self._backfill_evicted.discard(peer.id)
            try:
                peer.writer.close()
            except Exception:
                pass

    # -- message processing (ProcessMessage) ---------------------------

    async def _process_message(self, peer: Peer, command: str,
                               payload: bytes) -> None:
        log_print("net", "received: %s (%d bytes) peer=%d",
                  command, len(payload), peer.id)
        if INJECTOR.armed_for(NET_SITE):
            # BCP_FAULT_OPS=net: fail-rate models message loss at the
            # dispatch boundary, latency-spike a slow link (chaos drills).
            # Latency is awaited here — on_call's blocking sleep would
            # stall the whole event loop, not one link.
            lat = INJECTOR.latency(NET_SITE)
            if lat:
                await asyncio.sleep(lat)
            else:
                try:
                    INJECTOR.on_call(NET_SITE)
                except InjectedFault:
                    self.net_stats["net_faults_injected"] += 1
                    log_print("net", "dropped %s from peer=%d (injected "
                              "net fault)", command, peer.id)
                    return
        handler = getattr(self, f"_msg_{command}", None)
        if handler is None:
            return  # unknown messages are ignored, like the reference
        result = handler(peer, payload)
        if asyncio.iscoroutine(result):  # bulk-serving handlers drain
            await result

    def _msg_version(self, peer: Peer, payload: bytes) -> None:
        # score=0: benign protocol hygiene, not misbehavior — the raise
        # still ends the connection, but an operator addnode'ing the
        # node's own address must not show up as a stream of phantom
        # discharges in the attack counters
        if peer.version is not None:
            raise NetMessageError("duplicate version", score=0)
        version = VersionPayload.parse(payload)
        if version.nonce == self._nonce:
            raise NetMessageError("connected to self", score=0)
        peer.version = version
        peer.relay_txs = version.relay
        if not peer.outbound:
            peer.send("version", self._version_payload().serialize())
        peer.send("verack")

    def _msg_verack(self, peer: Peer, payload: bytes) -> None:
        peer.got_verack = True
        # BIP130: ask for headers-first block announcements (we already
        # process unsolicited headers via _msg_headers)
        peer.send("sendheaders")
        # BIP152: offer compact-block relay, version 1, low-bandwidth
        # (announce=0: we ask peers to announce via headers/inv and pull
        # cmpctblock on demand; peers may still sendcmpct(1) at us)
        peer.send("sendcmpct", struct.pack("<BQ", 0, 1))
        # BIP133: tell the peer our relay floor so it doesn't waste invs
        peer.send("feefilter",
                  struct.pack("<Q", self.node.min_relay_fee_rate))
        if peer.outbound:
            # handshake success: promote in addrman, harvest its peers,
            # and reset the dial loop's backoff to its base interval
            host, _, port = peer.addr.rpartition(":")
            self.addrman.good(host, int(port))
            self._dial_backoff.reset()
            peer.send("getaddr")
        # start headers sync (the reference sends getheaders on verack)
        with self.node.cs_main:
            locator = self.node.chainstate.chain.get_locator()
        peer.send("getheaders", ser_getheaders(locator))

    def _msg_ping(self, peer: Peer, payload: bytes) -> None:
        peer.send("pong", ser_ping(deser_ping(payload)))

    def _msg_pong(self, peer: Peer, payload: bytes) -> None:
        pass

    def _msg_sendheaders(self, peer: Peer, payload: bytes) -> None:
        """BIP130: peer wants new-block announcements as headers messages
        instead of inv (net_processing.cpp SENDHEADERS handling)."""
        peer.prefers_headers = True

    def _msg_getheaders(self, peer: Peer, payload: bytes) -> None:
        locator, hash_stop = deser_getheaders(payload)
        with self.node.cs_main:
            cs = self.node.chainstate
            start = None
            for h in locator:
                idx = cs.block_index.get(h)
                if idx is not None and idx in cs.chain:
                    start = idx
                    break
            height = (start.height + 1) if start is not None else 0
            headers = []
            while len(headers) < MAX_HEADERS_RESULTS:
                idx = cs.chain[height]
                if idx is None:
                    break
                headers.append(idx.header)
                if idx.hash == hash_stop:
                    break
                height += 1
        peer.send("headers", ser_headers(headers))

    # headers batches below this size aren't worth a device dispatch for
    # the PoW pre-filter (the per-header host check in accept_block_header
    # covers them anyway)
    HEADERS_POW_BATCH_MIN = 16

    def _msg_headers(self, peer: Peer, payload: bytes) -> None:
        headers = deser_headers(payload)
        if not headers:
            return
        if len(headers) >= self.HEADERS_POW_BATCH_MIN:
            # batched context-free PoW over the whole announcement in one
            # supervised dispatch (consensus/pow.check_headers_pow_batch):
            # a 2000-header IBD batch with any bad-PoW header is rejected
            # before per-header context work, and a dead backend degrades
            # to host hashing with the identical verdict
            ok = check_headers_pow_batch(
                [h.serialize() for h in headers], self.node.params.consensus
            )
            if not all(ok):
                raise NetMessageError("invalid header: high-hash")
        want = []  # ordered for the getdata; the set gives O(1) dedupe
        want_set = set()
        truncated = False  # batch cut short on a clock-subjective reject
        progressed = False  # batch taught us at least one NEW header
        with self.node.cs_main:
            cs = self.node.chainstate
            for header in headers:
                try:
                    # newness via index growth, not a pre-hash — an extra
                    # get_hash() here would double-SHA every header of a
                    # 2000-header IBD batch a second time
                    before = len(cs.block_index)
                    idx = cs.accept_block_header(header)
                    progressed = progressed or len(cs.block_index) > before
                except BlockValidationError as e:
                    if e.reason == "prev-blk-not-found":
                        # out of order — graduated misbehavior, rate-limited
                        # (MAX_UNCONNECTING_HEADERS): honest peers hit this
                        # in bursts around reorgs/our IBD and their counter
                        # resets on every NEW connecting header, while a
                        # garbage-replayer's only ever grows, accumulating
                        # to the threshold. Then restart sync from our
                        # locator. Nothing was reserved for this batch:
                        # getdata bookkeeping happens after the loop.
                        peer.unconnecting_headers += 1
                        if peer.unconnecting_headers % \
                                self.max_unconnecting == 0:
                            self.misbehaving(
                                peer, CHARGE_NONCONNECTING_HEADERS,
                                "non-connecting-headers")
                            if peer.discharged:
                                return
                        locator = cs.chain.get_locator()
                        peer.send("getheaders", ser_getheaders(locator))
                        return
                    if e.reason == "time-too-new":
                        # clock-subjective: as likely our skewed clock as
                        # their bad header (the block path exempts it for
                        # the same reason) — stop processing the batch
                        # but keep the connection uncharged; the header
                        # becomes acceptable as our clock catches up and
                        # the peer re-announces. truncated guards the
                        # continuation getheaders below: headers[-1] was
                        # never accepted, so it has no index entry.
                        truncated = True
                        break
                    raise NetMessageError(f"invalid header: {e.reason}") from None
                if not (idx.status & BlockStatus.HAVE_DATA):
                    if idx.hash in self._requested_blocks:
                        # fallback announcer for an in-flight download —
                        # the stall detector re-requests from it. (For
                        # fresh hashes the source is registered at
                        # dispatch below, not here: a batch cut short by
                        # the non-connecting return never dispatches, and
                        # registering then would leak entries that no
                        # pruning site ever visits.)
                        self._block_sources.setdefault(
                            idx.hash, set()).add(peer.id)
                    elif idx.hash not in want_set:
                        want.append(idx.hash)
                        want_set.add(idx.hash)
            # only a batch that taught us at least one NEW connecting
            # header redeems the counter. Resetting per accepted header
            # would let an attacker evade the graduated charge by
            # prepending genesis to every garbage batch (the
            # non-connecting path above returns before reaching here),
            # and resetting on any completed batch would let it
            # alternate garbage batches with replays of known headers —
            # replaying what we already know is not redemption.
            if progressed:
                peer.unconnecting_headers = 0
        if want:
            self._request_or_park(peer, want)
        if len(headers) == MAX_HEADERS_RESULTS and not truncated:  # more?
            with self.node.cs_main:
                locator = self.node.chainstate.chain.get_locator(
                    self.node.chainstate.block_index[headers[-1].get_hash()]
                )
            peer.send("getheaders", ser_getheaders(locator))

    def _msg_inv(self, peer: Peer, payload: bytes) -> None:
        items = deser_inv(payload)
        want_tx = []
        ask_headers = False
        with self.node.cs_main:
            cs = self.node.chainstate
            for inv_type, h in items:
                peer.known_invs.add(h)
                if inv_type == MSG_BLOCK:
                    idx = cs.block_index.get(h)
                    if idx is None or not (idx.status & BlockStatus.HAVE_DATA):
                        ask_headers = True  # headers-first sync
                elif inv_type == MSG_TX:
                    if h not in self.node.mempool:
                        want_tx.append(h)
            locator = cs.chain.get_locator() if ask_headers else None
        if ask_headers:
            peer.send("getheaders", ser_getheaders(locator))
        if want_tx:
            peer.send("getdata", ser_inv([(MSG_TX, h) for h in want_tx]))

    async def _msg_getdata(self, peer: Peer, payload: bytes) -> None:
        # async handler: a 2000-block IBD getdata would otherwise buffer
        # every serialized block in the transport at once — drain after each
        # send for backpressure (the reference bounds this with its
        # per-peer send-buffer limit, net.cpp nSendBufferMaxSize)
        items = deser_inv(payload)
        for inv_type, h in items:
            if inv_type == MSG_BLOCK:
                with self.node.cs_main:
                    raw = self.node.block_store.get_block(h)
                if raw is not None:
                    peer.send("block", raw)
                    await peer.writer.drain()
            elif inv_type == MSG_CMPCT_BLOCK:
                with self.node.cs_main:
                    raw = self.node.block_store.get_block(h)
                if raw is not None:
                    from .compact import HeaderAndShortIDs

                    peer.send("cmpctblock", HeaderAndShortIDs.from_block(
                        CBlock.from_bytes(raw)).serialize())
                    await peer.writer.drain()
            elif inv_type == MSG_FILTERED_BLOCK:
                # BIP37: merkleblock + the matched txs (net_processing.cpp
                # ProcessGetData MSG_FILTERED_BLOCK branch). No filter
                # loaded → ignore the request, like the reference.
                if peer.bloom_filter is None:
                    continue
                with self.node.cs_main:
                    raw = self.node.block_store.get_block(h)
                    if raw is None:
                        continue
                    block = CBlock.from_bytes(raw)
                    from ..consensus.merkleblock import CMerkleBlock

                    mb = CMerkleBlock.from_block(block, peer.bloom_filter)
                peer.send("merkleblock", mb.serialize())
                # always follow with the matched txs: once mined they are
                # gone from the mempool, so a skipped send here would be
                # the peer's last chance to ever obtain them
                matched = set(mb.matched_txids)
                for tx in block.vtx:
                    if tx.txid in matched:
                        peer.send("tx", tx.serialize())
                await peer.writer.drain()
            elif inv_type == MSG_TX:
                with self.node.cs_main:
                    tx = self.node.mempool.get_tx(h)
                if tx is None:
                    # mapRelay: a just-mined tx can still be served
                    kept = self._relay_memory.get(h)
                    if kept is not None and kept[1] > time.time():
                        tx = kept[0]
                if tx is not None:
                    peer.send("tx", tx.serialize())
                    await peer.writer.drain()

    def _msg_block(self, peer: Peer, payload: bytes) -> None:
        try:
            block = CBlock.from_bytes(payload)
        except Exception:
            raise NetMessageError("undecodable block") from None
        self._note_block_arrival(peer, block.get_hash(),
                                 wire_bytes=HEADER_SIZE + len(payload))
        self._process_block_obj(peer, block)

    def _msg_tx(self, peer: Peer, payload: bytes) -> None:
        try:
            tx = CTransaction.from_bytes(payload)
        except Exception:
            raise NetMessageError("undecodable tx") from None
        peer.known_invs.add(tx.txid)
        with self.node.cs_main:
            self._accept_tx(peer, tx)

    def _accept_tx(self, peer: Peer, tx: CTransaction) -> None:
        """ATMP + the mapOrphanTransactions dance (net_processing.cpp:~900):
        a tx with unknown inputs parks in a bounded orphan pool and is
        retried when any parent is accepted; accepted txs relay onward and
        trigger orphan reprocessing. Caller holds cs_main."""
        try:
            self.node.accept_to_mempool(tx)
        except MempoolError as e:
            if e.reason == "missing-inputs":
                self._add_orphan(peer, tx)
            else:
                log_print("net", "tx %s rejected: %s", tx.txid_hex[:16], e.reason)
                if peer is not None:
                    code = (REJECT_INSUFFICIENTFEE
                            if "fee" in e.reason else REJECT_INVALID)
                    self._send_reject(peer, "tx", code, e.reason, tx.txid)
                    # graduated charge for unambiguous consensus
                    # violations only — policy rejects (fees, limits,
                    # duplicates, standardness, the POLICY_BAD_TXNS
                    # reasons) are not misbehavior. Script failures are
                    # NEVER charged: mempool verification runs STANDARD
                    # flags (a superset of consensus — LOW_S, CLEANSTACK,
                    # MINIMALDATA...), so a "mandatory-script-verify-
                    # flag-failed" reject may be a consensus-valid tx
                    # that merely violates policy (e.g. a high-S
                    # signature), and charging it would evict honest
                    # relayers. The reference re-verifies with
                    # mandatory-only flags before punishing; lacking that
                    # second pass, the ambiguity forfeits the charge.
                    if ((e.reason.startswith("bad-txns")
                            and e.reason not in POLICY_BAD_TXNS)
                            or e.reason == "coinbase"):
                        self.misbehaving(peer, CHARGE_INVALID_TX,
                                         "invalid-tx")
            return
        self.relay_tx(tx.txid, skip_peer=peer.id if peer else 0)
        # any orphans that spend this tx can be retried now — attributed
        # to the peer that SENT each orphan (a consensus-invalid orphan
        # must charge its own relayer, not whoever supplied the parent)
        dependents = [
            e for e in self._orphans.values()
            if any(i.prevout.hash == tx.txid for i in e[0].vin)
        ]
        for orphan_tx, source_id, _size, _added in dependents:
            self._remove_orphan(orphan_tx.txid)
            self._accept_tx(self.peers.get(source_id), orphan_tx)

    def _msg_mempool(self, peer: Peer, payload: bytes) -> None:
        """BIP35 'mempool': answer with an inv of current mempool txids
        (bloom-filtered when the peer loaded one, like the reference)."""
        with self.node.cs_main:
            if peer.bloom_filter is not None:
                txids = [
                    txid for txid, e in self.node.mempool.entries.items()
                    if peer.bloom_filter.is_relevant_and_update(e.tx)
                ]
            else:
                txids = list(self.node.mempool.entries)
        if txids:
            peer.send("inv", ser_inv([(MSG_TX, h) for h in txids[:50_000]]))

    # -- BIP37 bloom filtering (net_processing.cpp FILTERLOAD/ADD/CLEAR) --

    def _msg_filterload(self, peer: Peer, payload: bytes) -> None:
        try:
            f = deser_filterload(payload)
        except Exception:
            raise NetMessageError("bad filterload") from None
        if not f.is_within_size_constraints():
            raise NetMessageError("oversized bloom filter")
        peer.bloom_filter = f
        peer.relay_txs = True

    def _msg_filteradd(self, peer: Peer, payload: bytes) -> None:
        from ..consensus.serialize import ByteReader, deser_compact_size

        try:
            r = ByteReader(payload)
            n = deser_compact_size(r)
            data = r.read_bytes(n)
        except Exception:
            raise NetMessageError("bad filteradd") from None
        # MAX_SCRIPT_ELEMENT_SIZE bound, and adding without a loaded filter
        # is misbehavior (net_processing.cpp)
        if len(data) > 520 or peer.bloom_filter is None:
            raise NetMessageError("filteradd without filter or oversized")
        peer.bloom_filter.insert(data)

    def _msg_filterclear(self, peer: Peer, payload: bytes) -> None:
        peer.bloom_filter = None
        peer.relay_txs = True  # "relay all transactions" per BIP37

    # -- BIP152 compact blocks (net_processing.cpp SENDCMPCT/CMPCTBLOCK/
    # GETBLOCKTXN/BLOCKTXN) ----------------------------------------------

    # -- addr gossip (net_processing.cpp ADDR/GETADDR, CAddrMan) ---------

    def _msg_addr(self, peer: Peer, payload: bytes) -> None:
        from .protocol import deser_addr_entries

        entries = deser_addr_entries(payload)
        now = int(time.time())
        for t, services, host, port in entries:
            if host == "::" or port == 0:
                continue
            # clamp absurd timestamps like CAddrMan (10-min penalty
            # skipped); the gossiping peer is the SOURCE — it determines
            # which 64 new buckets the entry may land in (eclipse defense)
            self.addrman.add(host, port, services, min(t, now),
                             source=peer.addr.rsplit(":", 1)[0])
        log_print("net", "peer=%d addr: %d entries (%d known)",
                  peer.id, len(entries), len(self.addrman))

    def _msg_getaddr(self, peer: Peer, payload: bytes) -> None:
        from .protocol import ser_addr_entries

        entries = [
            (a.time, a.services, a.host, a.port)
            for a in self.addrman.addresses()
        ]
        if entries:
            peer.send("addr", ser_addr_entries(entries))

    async def _open_connections_loop(self) -> None:
        """ThreadOpenConnections (net.cpp): keep dialing addrman candidates
        until the outbound target is met. Paced by the shared jittered
        exponential backoff: every dial that does not produce a handshake
        grows the next sleep (to 60 s max), and a completed handshake
        (_msg_verack) resets it — a dead or unreachable candidate set backs
        the node off instead of burning a fixed-interval dial loop."""
        while True:
            await asyncio.sleep(self._dial_backoff.next())
            outbound = [p for p in self.peers.values() if p.outbound]
            if (len(outbound) >= self.max_outbound
                    or len(self.peers) >= self.max_connections):
                self._dial_backoff.reset()  # healthy: keep the base poll
                continue
            connected = {p.addr for p in self.peers.values()}
            candidate = self.addrman.select(exclude=connected)
            if candidate is None or self.is_banned(candidate.host):
                continue
            self.addrman.attempt(candidate.host, candidate.port)
            try:
                # bound the TCP connect so one black-holed advertised
                # address can't stall the dial loop for minutes
                await asyncio.wait_for(
                    self._dial(candidate.host, candidate.port), timeout=10)
            except asyncio.TimeoutError:
                log_print("net", "dial %s:%d timed out",
                          candidate.host, candidate.port)

    def _msg_feefilter(self, peer: Peer, payload: bytes) -> None:
        """BIP133: peer's minimum announce feerate (sat/kB)."""
        if len(payload) != 8:
            raise NetMessageError("bad feefilter")
        (peer.min_fee_filter,) = struct.unpack("<Q", payload)

    def _send_reject(self, peer: Peer, message: str, code: int,
                     reason: str, h: bytes = b"") -> None:
        """BIP61 reject (net_processing.cpp PushMessage(REJECT, ...))."""
        from ..consensus.serialize import ser_compact_size

        msg = message.encode()
        rsn = reason.encode()[:111]  # MAX_REJECT_MESSAGE_LENGTH
        payload = (ser_compact_size(len(msg)) + msg + bytes([code])
                   + ser_compact_size(len(rsn)) + rsn + h)
        try:
            peer.send("reject", payload)
        except Exception:
            pass

    def _msg_reject(self, peer: Peer, payload: bytes) -> None:
        """Incoming rejects are logged, never acted on (like the
        reference's -debug=net logging of REJECT)."""
        log_print("net", "peer=%d reject: %s", peer.id, payload[:64].hex())

    def _msg_sendcmpct(self, peer: Peer, payload: bytes) -> None:
        if len(payload) != 9:
            raise NetMessageError("bad sendcmpct")
        announce, version = struct.unpack("<BQ", payload)
        if version == 1:  # other versions are ignored, like the reference
            peer.cmpct_announce = bool(announce)

    def _msg_cmpctblock(self, peer: Peer, payload: bytes) -> None:
        from .compact import BlockTransactionsRequest, HeaderAndShortIDs
        from ..consensus.serialize import ByteReader

        try:
            hsids = HeaderAndShortIDs.deserialize(ByteReader(payload))
        except Exception:
            raise NetMessageError("undecodable cmpctblock") from None
        h = hsids.header.get_hash()
        with self.node.cs_main:
            cs = self.node.chainstate
            idx = cs.block_index.get(h)
            if idx is not None and (idx.status & BlockStatus.HAVE_DATA):
                return  # already have it
            # header must be valid before we spend effort reconstructing
            try:
                cs.accept_block_header(hsids.header)
            except BlockValidationError as e:
                if e.reason == "prev-blk-not-found":
                    # can't contextually validate — fall back to headers sync
                    peer.send("getheaders",
                              ser_getheaders(cs.chain.get_locator()))
                    return
                if e.reason == "time-too-new":
                    # clock-subjective, same exemption as the headers and
                    # block paths: drop the announcement uncharged — with
                    # compact blocks as the default tip-relay mode, a
                    # skewed local clock would otherwise discharge every
                    # honest tip relayer
                    return
                raise NetMessageError(
                    f"invalid cmpctblock header: {e.reason}") from None
            # a compact announcement is a claim of having the block
            self._block_sources.setdefault(h, set()).add(peer.id)
            # map short IDs over the mempool
            from .compact import short_id, short_id_keys

            k0, k1 = short_id_keys(hsids.header, hsids.nonce)
            by_sid = {
                short_id(k0, k1, txid): e.tx
                for txid, e in self.node.mempool.entries.items()
            }
            block, missing = hsids.reconstruct(by_sid.get)
        if block is not None:
            self._note_block_arrival(peer, h,
                                     wire_bytes=HEADER_SIZE + len(payload))
            self._process_block_obj(peer, block)
            return
        if peer.pending_cmpct is not None:
            # a second announcement would orphan the in-flight
            # reconstruction — fetch the old block in full instead
            old_h = peer.pending_cmpct[0].header.get_hash()
            self._request_or_park(peer, [old_h])
        # keep the shortid->tx map so blocktxn doesn't re-hash the mempool
        peer.pending_cmpct = (hsids, by_sid)
        req = BlockTransactionsRequest(h, missing)
        peer.send("getblocktxn", req.serialize())

    def _msg_getblocktxn(self, peer: Peer, payload: bytes) -> None:
        from .compact import BlockTransactions, BlockTransactionsRequest
        from ..consensus.serialize import ByteReader

        try:
            req = BlockTransactionsRequest.deserialize(ByteReader(payload))
        except Exception:
            raise NetMessageError("bad getblocktxn") from None
        with self.node.cs_main:
            raw = self.node.block_store.get_block(req.block_hash)
        if raw is None:
            return
        block = CBlock.from_bytes(raw)
        try:
            txs = [block.vtx[i] for i in req.indexes]
        except IndexError:
            raise NetMessageError("getblocktxn index out of range") from None
        peer.send("blocktxn",
                  BlockTransactions(req.block_hash, txs).serialize())

    def _msg_blocktxn(self, peer: Peer, payload: bytes) -> None:
        from .compact import BlockTransactions
        from ..consensus.serialize import ByteReader

        try:
            bt = BlockTransactions.deserialize(ByteReader(payload))
        except Exception:
            raise NetMessageError("bad blocktxn") from None
        if peer.pending_cmpct is None:
            return  # unsolicited
        hsids, by_sid = peer.pending_cmpct
        if hsids.header.get_hash() == bt.block_hash:
            # this reply answers OUR getblocktxn — solicited bytes are
            # exempt from the flood ceiling (the reconstructed hash is
            # usually not in _requested_blocks, so _note_block_arrival's
            # solicited-exemption would not recognize it). Only the
            # MATCHING reply is exempt: a stream of mismatched "stale"
            # replies is attacker-chosen traffic and must keep counting,
            # or one dangling getblocktxn would neuter -maxrecvrate.
            peer.recv_window = max(0, peer.recv_window
                                   - (HEADER_SIZE + len(payload)))
        else:
            # stale reply for an overwritten reconstruction: fetch in
            # full — but ONLY a hash this peer actually announced (it is
            # attacker-controlled: registering an arbitrary 32-byte hash
            # in the download tracker would poison it with a block nobody
            # can ever deliver)
            if peer.id in self._block_sources.get(bt.block_hash, ()):
                self._request_or_park(peer, [bt.block_hash])
            return
        peer.pending_cmpct = None
        # retry reconstruction with the cached map + the supplied txs; the
        # shortid check inside reconstruct() rejects wrong fills
        from .compact import short_id, short_id_keys

        k0, k1 = short_id_keys(hsids.header, hsids.nonce)
        for tx in bt.txs:
            by_sid[short_id(k0, k1, tx.txid)] = tx
        block, missing = hsids.reconstruct(by_sid.get)
        if block is None:
            # reconstruction failed — fall back to a full block fetch,
            # through _request_blocks so the stall detector tracks it and
            # the delivered bytes count as solicited
            h = hsids.header.get_hash()
            self._request_or_park(peer, [h])
            return
        # wire_bytes=0: the flood exemption already happened above
        self._note_block_arrival(peer, block.get_hash())
        self._process_block_obj(peer, block)

    def _process_block_obj(self, peer: Peer, block: CBlock) -> None:
        """Shared block-acceptance tail for block/cmpctblock/blocktxn."""
        h = block.get_hash()
        peer.known_invs.add(h)
        with self.node.cs_main:
            # tip-relay serving (serving/sigservice): a reconstructed
            # block's non-mempool transactions get their sigchecks settled
            # through the shared service lanes first, so the connect below
            # probes them out of the sigcache instead of verifying inline.
            # Advisory only — prewarm gates itself (tip extension, live
            # mempool, REAL header PoW, merkle commitment) so garbage
            # bodies never buy interpreter time, and the connect stays
            # the authoritative verdict either way.
            if getattr(self.node, "sigservice", None) is not None:
                from ..serving import prewarm_block_sigs

                prewarm_block_sigs(self.node, block)
            try:
                # P2P block flow rides the pipelined engine (ISSUE 9):
                # competing tips speculatively connect as tree branches
                # (batches sharing the cross-block LanePacker) and the
                # live settle policy externalizes eagerly except inside
                # the -spechold fork-race grace window; with depth<=1
                # process_new_block_pipelined IS the serial engine
                cs = self.node.chainstate
                pipelined = getattr(cs, "process_new_block_pipelined",
                                    None)
                if pipelined is not None:
                    pipelined(block)
                    cs.settle_live()
                else:  # harness stubs pass a bare chainstate namespace
                    cs.process_new_block(block)
                self._block_sources.pop(h, None)  # landed — tracking done
            except BlockValidationError as e:
                if e.reason == "duplicate":
                    self._block_sources.pop(h, None)
                if e.reason not in ("duplicate", "prev-blk-not-found"):
                    log_print("net", "peer=%d sent invalid block %s: %s",
                              peer.id, hash_to_hex(h)[:16], e.reason)
                    self._send_reject(peer, "block", REJECT_INVALID,
                                      e.reason, h)
                    # a consensus-invalid block is an immediate discharge
                    # (net_processing.cpp Misbehaving(100)) — EXCEPT
                    # clock-subjective rejections: time-too-new is as
                    # likely our skewed clock as their bad block (the
                    # reference exempts BLOCK_TIME_FUTURE), and charging
                    # it would let a lagging local clock evict every
                    # honest relayer of the real tip one by one
                    if e.reason != "time-too-new":
                        self.misbehaving(peer, self.ban_threshold,
                                         "invalid-block")
                        # the DELIVERY was bad, but the block the header
                        # committed to may still be the honest chain's
                        # (e.g. a poisoned peer replayed a wanted hash
                        # with garbage txs — merkle mismatch). The
                        # arrival already untracked the download, so if
                        # the node still wants the hash (header accepted,
                        # no data, not marked failed — connect-time
                        # failures mark FAILED and never raise to here),
                        # park it for re-request from a healthy peer;
                        # otherwise one poisoned delivery per hash wedges
                        # IBD permanently. The deliverer is discharged
                        # above, so _tick never hands the hash back to it.
                        idx = self.node.chainstate.block_index.get(h)
                        if (idx is not None
                                and not (idx.status & BlockStatus.HAVE_DATA)
                                and not (idx.status
                                         & BlockStatus.FAILED_MASK)):
                            self._unrequested.add(h)

    # -- relay ----------------------------------------------------------

    def _on_tip_changed(self, tip) -> None:
        if tip is None:
            return
        header = tip.header

        def _announce():
            # runs on the event loop: peer-dict iteration is single-threaded
            # here, and the compact form is serialized lazily at most once
            cmpct_payload = None
            for peer in list(self.peers.values()):
                if not peer.handshaked or tip.hash in peer.known_invs:
                    continue
                peer.known_invs.add(tip.hash)
                try:
                    if peer.cmpct_announce:
                        if cmpct_payload is None:
                            with self.node.cs_main:
                                raw = self.node.block_store.get_block(tip.hash)
                            if raw is not None:
                                from .compact import HeaderAndShortIDs

                                cmpct_payload = HeaderAndShortIDs.from_block(
                                    CBlock.from_bytes(raw)).serialize()
                        if cmpct_payload is not None:
                            peer.send("cmpctblock", cmpct_payload)
                            continue
                    if peer.prefers_headers:  # BIP130 headers announce
                        peer.send("headers", ser_headers([header]))
                    else:
                        peer.send("inv", ser_inv([(MSG_BLOCK, tip.hash)]))
                except Exception:
                    pass
        if self.loop is not None:
            self.loop.call_soon_threadsafe(_announce)

    def _broadcast_inv(self, inv_type: int, h: bytes, skip_peer: int = 0) -> None:
        # tx relay honors BIP37: a peer with a loaded bloom filter only
        # hears about relevant txs; version.relay=False without a filter
        # suppresses tx invs entirely (net_processing.cpp SendMessages)
        tx = None
        fee_rate = 0
        if inv_type == MSG_TX:
            with self.node.cs_main:
                entry = self.node.mempool.get(h)
                if entry is not None:
                    tx = entry.tx
                    fee_rate = entry.fee * 1000 // max(entry.size, 1)
                    # mapRelay: remember for serving getdata post-mining
                    self._relay_memory[h] = (
                        tx, time.time() + RELAY_TX_CACHE_TIME)

        def _want(peer: Peer) -> bool:
            if inv_type != MSG_TX:
                return True
            if peer.min_fee_filter and fee_rate < peer.min_fee_filter:
                return False  # BIP133
            if peer.bloom_filter is not None:
                return tx is not None and \
                    peer.bloom_filter.is_relevant_and_update(tx)
            return peer.relay_txs

        def _do():
            for peer in self.peers.values():
                if peer.id == skip_peer or not peer.handshaked:
                    continue
                if h in peer.known_invs or not _want(peer):
                    continue
                peer.known_invs.add(h)
                try:
                    peer.send("inv", ser_inv([(inv_type, h)]))
                except Exception:
                    pass
        self.loop.call_soon_threadsafe(_do)

    def relay_block(self, h: bytes, skip_peer: int = 0) -> None:
        self._broadcast_inv(MSG_BLOCK, h, skip_peer)

    def relay_tx(self, h: bytes, skip_peer: int = 0) -> None:
        self._broadcast_inv(MSG_TX, h, skip_peer)
