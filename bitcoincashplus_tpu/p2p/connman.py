"""Connection manager + message processing.

Reference: src/net.cpp (CConnman: accept loop, peer lifecycle — the
reference's ThreadSocketHandler/ThreadMessageHandler pair is one asyncio
event loop on a dedicated thread here), src/net_processing.cpp
(ProcessMessage: the per-command logic below follows its shape, minimal
subset; headers-first sync as in the reference's getheaders/headers/
getdata flow). Chainstate/mempool access happens under node.cs_main.

Fault handling: any NetMessageError (bad magic/checksum/payload) =
Misbehaving → disconnect, like the reference's ban-score discharge.
"""

from __future__ import annotations

import asyncio
import os
import secrets
import struct
import threading
import time
from typing import Optional

from ..consensus.block import CBlock
from ..consensus.serialize import hash_to_hex
from ..consensus.tx import CTransaction
from ..consensus.pow import check_headers_pow_batch
from ..mempool.mempool import MempoolError
from ..util.faults import Backoff
from ..util.log import log_print, log_printf
from ..validation.chain import BlockStatus
from ..validation.chainstate import BlockValidationError
from .bloom import (
    MAX_BLOOM_FILTER_SIZE,
    CBloomFilter,
    deser_filterload,
)
from .protocol import (
    HEADER_SIZE,
    MAX_HEADERS_RESULTS,
    MSG_BLOCK,
    MSG_CMPCT_BLOCK,
    MSG_FILTERED_BLOCK,
    MSG_TX,
    MessageHeader,
    NetMessageError,
    VersionPayload,
    check_payload,
    deser_getheaders,
    deser_headers,
    deser_inv,
    deser_ping,
    pack_message,
    ser_getheaders,
    ser_headers,
    ser_inv,
    ser_ping,
)


MAX_ORPHAN_TX = 100  # DEFAULT_MAX_ORPHAN_TRANSACTIONS
PING_INTERVAL = 120       # net.cpp PING_INTERVAL
TIMEOUT_INTERVAL = 1200   # net.cpp TIMEOUT_INTERVAL (20 min)
RELAY_TX_CACHE_TIME = 900  # mapRelay retention (15 min, net_processing.cpp)

# BIP61 reject codes (src/consensus/validation.h REJECT_*)
REJECT_MALFORMED = 0x01
REJECT_INVALID = 0x10
REJECT_DUPLICATE = 0x12
REJECT_NONSTANDARD = 0x40
REJECT_INSUFFICIENTFEE = 0x42

class Peer:
    """CNode — one connected peer."""

    _next_id = 0

    def __init__(self, connman: "CConnman", reader, writer, outbound: bool):
        Peer._next_id += 1
        self.id = Peer._next_id
        self.connman = connman
        self.reader = reader
        self.writer = writer
        self.outbound = outbound
        peername = writer.get_extra_info("peername") or ("?", 0)
        self.addr = f"{peername[0]}:{peername[1]}"
        self.version: Optional[VersionPayload] = None
        self.got_verack = False
        self.prefers_headers = False  # BIP130 sendheaders
        # BIP37 SPV state: None = no filter (relay per relay_txs);
        # set by filterload, updated by matches per nFlags
        self.bloom_filter: Optional[CBloomFilter] = None
        # fRelayTxes: seeded from the version message's relay byte;
        # filterload/filterclear force it back on (BIP37 semantics)
        self.relay_txs = True
        # BIP152: peer sent sendcmpct(announce=1) → announce new tips as
        # cmpctblock (high-bandwidth mode)
        self.cmpct_announce = False
        # one in-flight compact-block reconstruction (PartiallyDownloadedBlock)
        self.pending_cmpct = None
        # BIP133 feefilter: don't announce txs below this rate (sat/kB)
        self.min_fee_filter = 0
        self.known_invs: set[bytes] = set()
        self.connected_at = time.time()
        self.last_recv = 0.0
        self.last_send = 0.0
        self.bytes_recv = 0
        self.bytes_sent = 0

    @property
    def handshaked(self) -> bool:
        return self.version is not None and self.got_verack

    def send(self, command: str, payload: bytes = b"") -> None:
        raw = pack_message(self.connman.magic, command, payload)
        self.writer.write(raw)
        self.bytes_sent += len(raw)
        self.connman.bytes_sent += len(raw)
        self.last_send = time.time()

    def info(self) -> dict:
        """getpeerinfo row (src/rpc/net.cpp)."""
        return {
            "id": self.id,
            "addr": self.addr,
            "inbound": not self.outbound,
            "version": self.version.version if self.version else 0,
            "subver": self.version.user_agent if self.version else "",
            "startingheight": self.version.start_height if self.version else -1,
            "conntime": int(self.connected_at),
            "bytessent": self.bytes_sent,
            "bytesrecv": self.bytes_recv,
        }


class CConnman:
    def __init__(self, node, bind_host: str = "127.0.0.1", listen_port: int = 0):
        self.node = node
        self.magic = node.params.netmagic
        self.bind_host = bind_host
        self.listen_port = listen_port  # 0 = don't listen
        self.port = 0
        self.peers: dict[int, Peer] = {}
        self.bytes_recv = 0
        self.bytes_sent = 0
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        # in-flight block downloads: hash -> requesting peer id. Entries are
        # dropped on block arrival AND on that peer's disconnect — otherwise
        # an unclean hangup would leave the hash "requested" forever and no
        # other peer could ever be asked for it (sync deadlock).
        self._requested_blocks: dict[bytes, int] = {}
        self._nonce = secrets.randbits(64)  # self-connect detection
        # CConnman/BanMan (src/banman.cpp): ip -> ban-expiry unix time.
        # Host granularity (no CIDR) matching how we track peers.
        self._banned: dict[str, float] = {}
        self.bantime = 86400  # -bantime default
        # mapOrphanTransactions (net_processing.cpp): txs whose inputs we
        # don't know yet, bounded FIFO
        self._orphans: dict[bytes, CTransaction] = {}
        # -addnode / addnode RPC "add" targets (vAddedNodes, net.cpp)
        self.added_nodes: list[str] = []
        # mapRelay (net_processing.cpp): recently relayed txs kept
        # RELAY_TX_CACHE_TIME so getdata can be served after the tx leaves
        # the mempool (e.g. it was just mined)
        self._relay_memory: dict[bytes, tuple[CTransaction, float]] = {}
        # CAddrMan + peers.dat (src/addrman.cpp, net.cpp DumpAddresses)
        from .addrman import AddrMan

        self.addrman = AddrMan()
        self._peers_path = os.path.join(node.datadir, "peers.json")
        n_loaded = self.addrman.load(self._peers_path)
        if n_loaded:
            log_print("net", "loaded %d addresses from peers.json", n_loaded)
        # -maxconnections (net.cpp nMaxConnections, default 125): inbound
        # accepts are refused at the cap
        self.max_connections = node.config.get_int("maxconnections", 125)
        # ThreadOpenConnections target, clamped by the total cap exactly
        # like the reference's min(MAX_OUTBOUND_CONNECTIONS, nMaxConnections)
        self.max_outbound = min(8, self.max_connections)
        # reconnect pacing (util/faults.Backoff): repeated dial failures
        # back the open-connections loop off exponentially with jitter
        # (instead of the old fixed 5 s poll hammering a dead candidate
        # list); any completed handshake resets it to the base interval
        self._dial_backoff = Backoff(base=5.0, factor=2.0, maximum=60.0,
                                     jitter=0.5)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="p2p", daemon=True)
        self._thread.start()
        if not self._started.wait(30):
            raise RuntimeError("P2P event loop failed to start")
        self.node.chainstate.on_tip_changed.append(self._on_tip_changed)

    def _run(self) -> None:
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)
        if self.listen_port:  # 0 = -listen=0 (outbound only)
            self.loop.run_until_complete(self._start_server())
        self.loop.create_task(self._keepalive_loop())
        self.loop.create_task(self._open_connections_loop())
        self._started.set()
        self.loop.run_forever()
        # drain: close transports
        for task in asyncio.all_tasks(self.loop):
            task.cancel()
        self.loop.run_until_complete(asyncio.sleep(0))
        self.loop.close()

    async def _keepalive_loop(self) -> None:
        """InactivityCheck + PingPeriodicity (net.cpp:~1300): ping every
        PING_INTERVAL; drop peers silent past TIMEOUT_INTERVAL."""
        while True:
            await asyncio.sleep(PING_INTERVAL)
            now = time.time()
            # expire mapRelay entries in place — RPC threads insert into
            # this dict concurrently, so never rebind it
            for h, v in list(self._relay_memory.items()):
                if v[1] <= now:
                    self._relay_memory.pop(h, None)
            for peer in list(self.peers.values()):
                quiet = now - max(peer.last_recv, peer.connected_at)
                if quiet > TIMEOUT_INTERVAL:
                    log_print("net", "peer=%d inactivity timeout — dropping",
                              peer.id)
                    peer.writer.close()
                elif peer.handshaked:
                    try:
                        peer.send("ping", ser_ping(secrets.randbits(64)))
                    except Exception:
                        pass

    async def _start_server(self) -> None:
        self._server = await asyncio.start_server(
            self._on_inbound, self.bind_host, self.listen_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log_print("net", "P2P listening on %s:%d", self.bind_host, self.port)

    def close(self) -> None:
        if self.loop is None:
            return

        def _shutdown():
            for peer in list(self.peers.values()):
                peer.writer.close()
            if self._server is not None:
                self._server.close()
            self.loop.stop()

        self.loop.call_soon_threadsafe(_shutdown)
        self._thread.join(10)
        try:
            self.addrman.save(self._peers_path)  # DumpAddresses
        except OSError as e:
            log_printf("peers.json save failed: %r", e)

    # -- dialing --------------------------------------------------------

    def connect_to(self, host: str, port: int) -> None:
        asyncio.run_coroutine_threadsafe(self._dial(host, port), self.loop)

    async def _dial(self, host: str, port: int) -> None:
        try:
            reader, writer = await asyncio.open_connection(host, port)
        except OSError as e:
            log_print("net", "connect to %s:%d failed: %s", host, port, e)
            return
        peer = Peer(self, reader, writer, outbound=True)
        self.peers[peer.id] = peer
        peer.send("version", self._version_payload().serialize())
        asyncio.ensure_future(self._peer_loop(peer))

    async def _on_inbound(self, reader, writer) -> None:
        peername = writer.get_extra_info("peername") or ("?", 0)
        if self.is_banned(peername[0]) or \
                len(self.peers) >= self.max_connections:
            writer.close()
            return
        peer = Peer(self, reader, writer, outbound=False)
        self.peers[peer.id] = peer
        await self._peer_loop(peer)

    # -- ban list (src/banman.cpp) --------------------------------------

    def is_banned(self, ip: str) -> bool:
        until = self._banned.get(ip)
        if until is None:
            return False
        if time.time() > until:
            self._banned.pop(ip, None)
            return False
        return True

    def ban(self, ip: str, bantime: int = 0) -> None:
        self._banned[ip] = time.time() + (bantime or self.bantime)
        # drop any live connections from that host
        def _do():
            for peer in list(self.peers.values()):
                if peer.addr.rsplit(":", 1)[0] == ip:
                    peer.writer.close()
        if self.loop is not None:
            self.loop.call_soon_threadsafe(_do)

    def unban(self, ip: str) -> bool:
        return self._banned.pop(ip, None) is not None

    def banned(self) -> dict[str, float]:
        now = time.time()
        self._banned = {ip: t for ip, t in self._banned.items() if t > now}
        return dict(self._banned)

    def clear_banned(self) -> None:
        self._banned.clear()

    def ping_all(self) -> None:
        def _do():
            for peer in self.peers.values():
                if peer.handshaked:
                    try:
                        peer.send("ping", ser_ping(secrets.randbits(64)))
                    except Exception:
                        pass
        if self.loop is not None:
            self.loop.call_soon_threadsafe(_do)

    def disconnect(self, addr: str) -> None:
        def _do():
            for peer in list(self.peers.values()):
                if peer.addr == addr:
                    peer.writer.close()
        self.loop.call_soon_threadsafe(_do)

    def _version_payload(self) -> VersionPayload:
        with self.node.cs_main:
            height = self.node.chainstate.tip().height
        return VersionPayload(nonce=self._nonce, start_height=height)

    # -- per-peer receive loop -----------------------------------------

    async def _peer_loop(self, peer: Peer) -> None:
        try:
            while True:
                raw_header = await peer.reader.readexactly(HEADER_SIZE)
                header = MessageHeader.parse(raw_header, self.magic)
                payload = await peer.reader.readexactly(header.length)
                check_payload(header, payload)
                peer.bytes_recv += HEADER_SIZE + header.length
                self.bytes_recv += HEADER_SIZE + header.length
                peer.last_recv = time.time()
                await self._process_message(peer, header.command, payload)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer hung up
        except NetMessageError as e:
            # Misbehaving (src/net_processing.cpp): malformed traffic =>
            # immediate discharge/disconnect. Banning stays operator-driven
            # (setban) — everything dials loopback here, and auto-banning
            # 127.0.0.1 would take out every future peer on the host.
            log_print("net", "peer=%d misbehaving: %s — disconnecting", peer.id, e)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log_printf("P2P internal error peer=%d: %r", peer.id, e)
        finally:
            self.peers.pop(peer.id, None)
            # free this peer's in-flight block requests for other peers
            self._requested_blocks = {
                h: pid for h, pid in self._requested_blocks.items()
                if pid != peer.id
            }
            try:
                peer.writer.close()
            except Exception:
                pass

    # -- message processing (ProcessMessage) ---------------------------

    async def _process_message(self, peer: Peer, command: str,
                               payload: bytes) -> None:
        log_print("net", "received: %s (%d bytes) peer=%d",
                  command, len(payload), peer.id)
        handler = getattr(self, f"_msg_{command}", None)
        if handler is None:
            return  # unknown messages are ignored, like the reference
        result = handler(peer, payload)
        if asyncio.iscoroutine(result):  # bulk-serving handlers drain
            await result

    def _msg_version(self, peer: Peer, payload: bytes) -> None:
        if peer.version is not None:
            raise NetMessageError("duplicate version")
        version = VersionPayload.parse(payload)
        if version.nonce == self._nonce:
            raise NetMessageError("connected to self")
        peer.version = version
        peer.relay_txs = version.relay
        if not peer.outbound:
            peer.send("version", self._version_payload().serialize())
        peer.send("verack")

    def _msg_verack(self, peer: Peer, payload: bytes) -> None:
        peer.got_verack = True
        # BIP130: ask for headers-first block announcements (we already
        # process unsolicited headers via _msg_headers)
        peer.send("sendheaders")
        # BIP152: offer compact-block relay, version 1, low-bandwidth
        # (announce=0: we ask peers to announce via headers/inv and pull
        # cmpctblock on demand; peers may still sendcmpct(1) at us)
        peer.send("sendcmpct", struct.pack("<BQ", 0, 1))
        # BIP133: tell the peer our relay floor so it doesn't waste invs
        peer.send("feefilter",
                  struct.pack("<Q", self.node.min_relay_fee_rate))
        if peer.outbound:
            # handshake success: promote in addrman, harvest its peers,
            # and reset the dial loop's backoff to its base interval
            host, _, port = peer.addr.rpartition(":")
            self.addrman.good(host, int(port))
            self._dial_backoff.reset()
            peer.send("getaddr")
        # start headers sync (the reference sends getheaders on verack)
        with self.node.cs_main:
            locator = self.node.chainstate.chain.get_locator()
        peer.send("getheaders", ser_getheaders(locator))

    def _msg_ping(self, peer: Peer, payload: bytes) -> None:
        peer.send("pong", ser_ping(deser_ping(payload)))

    def _msg_pong(self, peer: Peer, payload: bytes) -> None:
        pass

    def _msg_sendheaders(self, peer: Peer, payload: bytes) -> None:
        """BIP130: peer wants new-block announcements as headers messages
        instead of inv (net_processing.cpp SENDHEADERS handling)."""
        peer.prefers_headers = True

    def _msg_getheaders(self, peer: Peer, payload: bytes) -> None:
        locator, hash_stop = deser_getheaders(payload)
        with self.node.cs_main:
            cs = self.node.chainstate
            start = None
            for h in locator:
                idx = cs.block_index.get(h)
                if idx is not None and idx in cs.chain:
                    start = idx
                    break
            height = (start.height + 1) if start is not None else 0
            headers = []
            while len(headers) < MAX_HEADERS_RESULTS:
                idx = cs.chain[height]
                if idx is None:
                    break
                headers.append(idx.header)
                if idx.hash == hash_stop:
                    break
                height += 1
        peer.send("headers", ser_headers(headers))

    # headers batches below this size aren't worth a device dispatch for
    # the PoW pre-filter (the per-header host check in accept_block_header
    # covers them anyway)
    HEADERS_POW_BATCH_MIN = 16

    def _msg_headers(self, peer: Peer, payload: bytes) -> None:
        headers = deser_headers(payload)
        if not headers:
            return
        if len(headers) >= self.HEADERS_POW_BATCH_MIN:
            # batched context-free PoW over the whole announcement in one
            # supervised dispatch (consensus/pow.check_headers_pow_batch):
            # a 2000-header IBD batch with any bad-PoW header is rejected
            # before per-header context work, and a dead backend degrades
            # to host hashing with the identical verdict
            ok = check_headers_pow_batch(
                [h.serialize() for h in headers], self.node.params.consensus
            )
            if not all(ok):
                raise NetMessageError("invalid header: high-hash")
        want = []
        with self.node.cs_main:
            cs = self.node.chainstate
            for header in headers:
                try:
                    idx = cs.accept_block_header(header)
                except BlockValidationError as e:
                    if e.reason == "prev-blk-not-found":
                        # out of order — un-reserve anything we queued for
                        # this batch (its getdata is never sent) and restart
                        # sync from our locator
                        for h in want:
                            self._requested_blocks.pop(h, None)
                        locator = cs.chain.get_locator()
                        peer.send("getheaders", ser_getheaders(locator))
                        return
                    raise NetMessageError(f"invalid header: {e.reason}") from None
                if not (idx.status & BlockStatus.HAVE_DATA) and \
                        idx.hash not in self._requested_blocks:
                    want.append(idx.hash)
                    self._requested_blocks[idx.hash] = peer.id
        if want:
            peer.send("getdata", ser_inv([(MSG_BLOCK, h) for h in want]))
        if len(headers) == MAX_HEADERS_RESULTS:  # there may be more
            with self.node.cs_main:
                locator = self.node.chainstate.chain.get_locator(
                    self.node.chainstate.block_index[headers[-1].get_hash()]
                )
            peer.send("getheaders", ser_getheaders(locator))

    def _msg_inv(self, peer: Peer, payload: bytes) -> None:
        items = deser_inv(payload)
        want_tx = []
        ask_headers = False
        with self.node.cs_main:
            cs = self.node.chainstate
            for inv_type, h in items:
                peer.known_invs.add(h)
                if inv_type == MSG_BLOCK:
                    idx = cs.block_index.get(h)
                    if idx is None or not (idx.status & BlockStatus.HAVE_DATA):
                        ask_headers = True  # headers-first sync
                elif inv_type == MSG_TX:
                    if h not in self.node.mempool:
                        want_tx.append(h)
            locator = cs.chain.get_locator() if ask_headers else None
        if ask_headers:
            peer.send("getheaders", ser_getheaders(locator))
        if want_tx:
            peer.send("getdata", ser_inv([(MSG_TX, h) for h in want_tx]))

    async def _msg_getdata(self, peer: Peer, payload: bytes) -> None:
        # async handler: a 2000-block IBD getdata would otherwise buffer
        # every serialized block in the transport at once — drain after each
        # send for backpressure (the reference bounds this with its
        # per-peer send-buffer limit, net.cpp nSendBufferMaxSize)
        items = deser_inv(payload)
        for inv_type, h in items:
            if inv_type == MSG_BLOCK:
                with self.node.cs_main:
                    raw = self.node.block_store.get_block(h)
                if raw is not None:
                    peer.send("block", raw)
                    await peer.writer.drain()
            elif inv_type == MSG_CMPCT_BLOCK:
                with self.node.cs_main:
                    raw = self.node.block_store.get_block(h)
                if raw is not None:
                    from .compact import HeaderAndShortIDs

                    peer.send("cmpctblock", HeaderAndShortIDs.from_block(
                        CBlock.from_bytes(raw)).serialize())
                    await peer.writer.drain()
            elif inv_type == MSG_FILTERED_BLOCK:
                # BIP37: merkleblock + the matched txs (net_processing.cpp
                # ProcessGetData MSG_FILTERED_BLOCK branch). No filter
                # loaded → ignore the request, like the reference.
                if peer.bloom_filter is None:
                    continue
                with self.node.cs_main:
                    raw = self.node.block_store.get_block(h)
                    if raw is None:
                        continue
                    block = CBlock.from_bytes(raw)
                    from ..consensus.merkleblock import CMerkleBlock

                    mb = CMerkleBlock.from_block(block, peer.bloom_filter)
                peer.send("merkleblock", mb.serialize())
                # always follow with the matched txs: once mined they are
                # gone from the mempool, so a skipped send here would be
                # the peer's last chance to ever obtain them
                matched = set(mb.matched_txids)
                for tx in block.vtx:
                    if tx.txid in matched:
                        peer.send("tx", tx.serialize())
                await peer.writer.drain()
            elif inv_type == MSG_TX:
                with self.node.cs_main:
                    tx = self.node.mempool.get_tx(h)
                if tx is None:
                    # mapRelay: a just-mined tx can still be served
                    kept = self._relay_memory.get(h)
                    if kept is not None and kept[1] > time.time():
                        tx = kept[0]
                if tx is not None:
                    peer.send("tx", tx.serialize())
                    await peer.writer.drain()

    def _msg_block(self, peer: Peer, payload: bytes) -> None:
        try:
            block = CBlock.from_bytes(payload)
        except Exception:
            raise NetMessageError("undecodable block") from None
        self._requested_blocks.pop(block.get_hash(), None)
        self._process_block_obj(peer, block)

    def _msg_tx(self, peer: Peer, payload: bytes) -> None:
        try:
            tx = CTransaction.from_bytes(payload)
        except Exception:
            raise NetMessageError("undecodable tx") from None
        peer.known_invs.add(tx.txid)
        with self.node.cs_main:
            self._accept_tx(peer, tx)

    def _accept_tx(self, peer: Peer, tx: CTransaction) -> None:
        """ATMP + the mapOrphanTransactions dance (net_processing.cpp:~900):
        a tx with unknown inputs parks in a bounded orphan pool and is
        retried when any parent is accepted; accepted txs relay onward and
        trigger orphan reprocessing. Caller holds cs_main."""
        try:
            self.node.accept_to_mempool(tx)
        except MempoolError as e:
            if e.reason == "missing-inputs":
                if len(self._orphans) >= MAX_ORPHAN_TX:
                    # evict a random-ish (FIFO) orphan like LimitOrphanTxSize
                    self._orphans.pop(next(iter(self._orphans)))
                self._orphans[tx.txid] = tx
                log_print("net", "orphan tx %s parked (%d pooled)",
                          tx.txid_hex[:16], len(self._orphans))
            else:
                log_print("net", "tx %s rejected: %s", tx.txid_hex[:16], e.reason)
                if peer is not None:
                    code = (REJECT_INSUFFICIENTFEE
                            if "fee" in e.reason else REJECT_INVALID)
                    self._send_reject(peer, "tx", code, e.reason, tx.txid)
            return
        self.relay_tx(tx.txid, skip_peer=peer.id if peer else 0)
        # any orphans that spend this tx can be retried now
        dependents = [
            o for o in self._orphans.values()
            if any(i.prevout.hash == tx.txid for i in o.vin)
        ]
        for o in dependents:
            self._orphans.pop(o.txid, None)
            self._accept_tx(peer, o)

    def _msg_mempool(self, peer: Peer, payload: bytes) -> None:
        """BIP35 'mempool': answer with an inv of current mempool txids
        (bloom-filtered when the peer loaded one, like the reference)."""
        with self.node.cs_main:
            if peer.bloom_filter is not None:
                txids = [
                    txid for txid, e in self.node.mempool.entries.items()
                    if peer.bloom_filter.is_relevant_and_update(e.tx)
                ]
            else:
                txids = list(self.node.mempool.entries)
        if txids:
            peer.send("inv", ser_inv([(MSG_TX, h) for h in txids[:50_000]]))

    # -- BIP37 bloom filtering (net_processing.cpp FILTERLOAD/ADD/CLEAR) --

    def _msg_filterload(self, peer: Peer, payload: bytes) -> None:
        try:
            f = deser_filterload(payload)
        except Exception:
            raise NetMessageError("bad filterload") from None
        if not f.is_within_size_constraints():
            raise NetMessageError("oversized bloom filter")
        peer.bloom_filter = f
        peer.relay_txs = True

    def _msg_filteradd(self, peer: Peer, payload: bytes) -> None:
        from ..consensus.serialize import ByteReader, deser_compact_size

        try:
            r = ByteReader(payload)
            n = deser_compact_size(r)
            data = r.read_bytes(n)
        except Exception:
            raise NetMessageError("bad filteradd") from None
        # MAX_SCRIPT_ELEMENT_SIZE bound, and adding without a loaded filter
        # is misbehavior (net_processing.cpp)
        if len(data) > 520 or peer.bloom_filter is None:
            raise NetMessageError("filteradd without filter or oversized")
        peer.bloom_filter.insert(data)

    def _msg_filterclear(self, peer: Peer, payload: bytes) -> None:
        peer.bloom_filter = None
        peer.relay_txs = True  # "relay all transactions" per BIP37

    # -- BIP152 compact blocks (net_processing.cpp SENDCMPCT/CMPCTBLOCK/
    # GETBLOCKTXN/BLOCKTXN) ----------------------------------------------

    # -- addr gossip (net_processing.cpp ADDR/GETADDR, CAddrMan) ---------

    def _msg_addr(self, peer: Peer, payload: bytes) -> None:
        from .protocol import deser_addr_entries

        entries = deser_addr_entries(payload)
        now = int(time.time())
        for t, services, host, port in entries:
            if host == "::" or port == 0:
                continue
            # clamp absurd timestamps like CAddrMan (10-min penalty
            # skipped); the gossiping peer is the SOURCE — it determines
            # which 64 new buckets the entry may land in (eclipse defense)
            self.addrman.add(host, port, services, min(t, now),
                             source=peer.addr.rsplit(":", 1)[0])
        log_print("net", "peer=%d addr: %d entries (%d known)",
                  peer.id, len(entries), len(self.addrman))

    def _msg_getaddr(self, peer: Peer, payload: bytes) -> None:
        from .protocol import ser_addr_entries

        entries = [
            (a.time, a.services, a.host, a.port)
            for a in self.addrman.addresses()
        ]
        if entries:
            peer.send("addr", ser_addr_entries(entries))

    async def _open_connections_loop(self) -> None:
        """ThreadOpenConnections (net.cpp): keep dialing addrman candidates
        until the outbound target is met. Paced by the shared jittered
        exponential backoff: every dial that does not produce a handshake
        grows the next sleep (to 60 s max), and a completed handshake
        (_msg_verack) resets it — a dead or unreachable candidate set backs
        the node off instead of burning a fixed-interval dial loop."""
        while True:
            await asyncio.sleep(self._dial_backoff.next())
            outbound = [p for p in self.peers.values() if p.outbound]
            if (len(outbound) >= self.max_outbound
                    or len(self.peers) >= self.max_connections):
                self._dial_backoff.reset()  # healthy: keep the base poll
                continue
            connected = {p.addr for p in self.peers.values()}
            candidate = self.addrman.select(exclude=connected)
            if candidate is None or self.is_banned(candidate.host):
                continue
            self.addrman.attempt(candidate.host, candidate.port)
            try:
                # bound the TCP connect so one black-holed advertised
                # address can't stall the dial loop for minutes
                await asyncio.wait_for(
                    self._dial(candidate.host, candidate.port), timeout=10)
            except asyncio.TimeoutError:
                log_print("net", "dial %s:%d timed out",
                          candidate.host, candidate.port)

    def _msg_feefilter(self, peer: Peer, payload: bytes) -> None:
        """BIP133: peer's minimum announce feerate (sat/kB)."""
        if len(payload) != 8:
            raise NetMessageError("bad feefilter")
        (peer.min_fee_filter,) = struct.unpack("<Q", payload)

    def _send_reject(self, peer: Peer, message: str, code: int,
                     reason: str, h: bytes = b"") -> None:
        """BIP61 reject (net_processing.cpp PushMessage(REJECT, ...))."""
        from ..consensus.serialize import ser_compact_size

        msg = message.encode()
        rsn = reason.encode()[:111]  # MAX_REJECT_MESSAGE_LENGTH
        payload = (ser_compact_size(len(msg)) + msg + bytes([code])
                   + ser_compact_size(len(rsn)) + rsn + h)
        try:
            peer.send("reject", payload)
        except Exception:
            pass

    def _msg_reject(self, peer: Peer, payload: bytes) -> None:
        """Incoming rejects are logged, never acted on (like the
        reference's -debug=net logging of REJECT)."""
        log_print("net", "peer=%d reject: %s", peer.id, payload[:64].hex())

    def _msg_sendcmpct(self, peer: Peer, payload: bytes) -> None:
        if len(payload) != 9:
            raise NetMessageError("bad sendcmpct")
        announce, version = struct.unpack("<BQ", payload)
        if version == 1:  # other versions are ignored, like the reference
            peer.cmpct_announce = bool(announce)

    def _msg_cmpctblock(self, peer: Peer, payload: bytes) -> None:
        from .compact import BlockTransactionsRequest, HeaderAndShortIDs
        from ..consensus.serialize import ByteReader

        try:
            hsids = HeaderAndShortIDs.deserialize(ByteReader(payload))
        except Exception:
            raise NetMessageError("undecodable cmpctblock") from None
        h = hsids.header.get_hash()
        with self.node.cs_main:
            cs = self.node.chainstate
            idx = cs.block_index.get(h)
            if idx is not None and (idx.status & BlockStatus.HAVE_DATA):
                return  # already have it
            # header must be valid before we spend effort reconstructing
            try:
                cs.accept_block_header(hsids.header)
            except BlockValidationError as e:
                if e.reason == "prev-blk-not-found":
                    # can't contextually validate — fall back to headers sync
                    peer.send("getheaders",
                              ser_getheaders(cs.chain.get_locator()))
                    return
                raise NetMessageError(
                    f"invalid cmpctblock header: {e.reason}") from None
            # map short IDs over the mempool
            from .compact import short_id, short_id_keys

            k0, k1 = short_id_keys(hsids.header, hsids.nonce)
            by_sid = {
                short_id(k0, k1, txid): e.tx
                for txid, e in self.node.mempool.entries.items()
            }
            block, missing = hsids.reconstruct(by_sid.get)
        if block is not None:
            self._requested_blocks.pop(h, None)
            self._process_block_obj(peer, block)
            return
        if peer.pending_cmpct is not None:
            # a second announcement would orphan the in-flight
            # reconstruction — fetch the old block in full instead
            old_h = peer.pending_cmpct[0].header.get_hash()
            peer.send("getdata", ser_inv([(MSG_BLOCK, old_h)]))
        # keep the shortid->tx map so blocktxn doesn't re-hash the mempool
        peer.pending_cmpct = (hsids, by_sid)
        req = BlockTransactionsRequest(h, missing)
        peer.send("getblocktxn", req.serialize())

    def _msg_getblocktxn(self, peer: Peer, payload: bytes) -> None:
        from .compact import BlockTransactions, BlockTransactionsRequest
        from ..consensus.serialize import ByteReader

        try:
            req = BlockTransactionsRequest.deserialize(ByteReader(payload))
        except Exception:
            raise NetMessageError("bad getblocktxn") from None
        with self.node.cs_main:
            raw = self.node.block_store.get_block(req.block_hash)
        if raw is None:
            return
        block = CBlock.from_bytes(raw)
        try:
            txs = [block.vtx[i] for i in req.indexes]
        except IndexError:
            raise NetMessageError("getblocktxn index out of range") from None
        peer.send("blocktxn",
                  BlockTransactions(req.block_hash, txs).serialize())

    def _msg_blocktxn(self, peer: Peer, payload: bytes) -> None:
        from .compact import BlockTransactions
        from ..consensus.serialize import ByteReader

        try:
            bt = BlockTransactions.deserialize(ByteReader(payload))
        except Exception:
            raise NetMessageError("bad blocktxn") from None
        if peer.pending_cmpct is None:
            return  # unsolicited
        hsids, by_sid = peer.pending_cmpct
        if hsids.header.get_hash() != bt.block_hash:
            # stale reply for an overwritten reconstruction: fetch in full
            peer.send("getdata", ser_inv([(MSG_BLOCK, bt.block_hash)]))
            return
        peer.pending_cmpct = None
        # retry reconstruction with the cached map + the supplied txs; the
        # shortid check inside reconstruct() rejects wrong fills
        from .compact import short_id, short_id_keys

        k0, k1 = short_id_keys(hsids.header, hsids.nonce)
        for tx in bt.txs:
            by_sid[short_id(k0, k1, tx.txid)] = tx
        block, missing = hsids.reconstruct(by_sid.get)
        if block is None:
            # reconstruction failed — fall back to a full block fetch
            h = hsids.header.get_hash()
            peer.send("getdata", ser_inv([(MSG_BLOCK, h)]))
            return
        self._requested_blocks.pop(block.get_hash(), None)
        self._process_block_obj(peer, block)

    def _process_block_obj(self, peer: Peer, block: CBlock) -> None:
        """Shared block-acceptance tail for block/cmpctblock/blocktxn."""
        h = block.get_hash()
        peer.known_invs.add(h)
        with self.node.cs_main:
            try:
                self.node.chainstate.process_new_block(block)
            except BlockValidationError as e:
                if e.reason not in ("duplicate", "prev-blk-not-found"):
                    log_print("net", "peer=%d sent invalid block %s: %s",
                              peer.id, hash_to_hex(h)[:16], e.reason)
                    self._send_reject(peer, "block", REJECT_INVALID,
                                      e.reason, h)

    # -- relay ----------------------------------------------------------

    def _on_tip_changed(self, tip) -> None:
        if tip is None:
            return
        header = tip.header

        def _announce():
            # runs on the event loop: peer-dict iteration is single-threaded
            # here, and the compact form is serialized lazily at most once
            cmpct_payload = None
            for peer in list(self.peers.values()):
                if not peer.handshaked or tip.hash in peer.known_invs:
                    continue
                peer.known_invs.add(tip.hash)
                try:
                    if peer.cmpct_announce:
                        if cmpct_payload is None:
                            with self.node.cs_main:
                                raw = self.node.block_store.get_block(tip.hash)
                            if raw is not None:
                                from .compact import HeaderAndShortIDs

                                cmpct_payload = HeaderAndShortIDs.from_block(
                                    CBlock.from_bytes(raw)).serialize()
                        if cmpct_payload is not None:
                            peer.send("cmpctblock", cmpct_payload)
                            continue
                    if peer.prefers_headers:  # BIP130 headers announce
                        peer.send("headers", ser_headers([header]))
                    else:
                        peer.send("inv", ser_inv([(MSG_BLOCK, tip.hash)]))
                except Exception:
                    pass
        if self.loop is not None:
            self.loop.call_soon_threadsafe(_announce)

    def _broadcast_inv(self, inv_type: int, h: bytes, skip_peer: int = 0) -> None:
        # tx relay honors BIP37: a peer with a loaded bloom filter only
        # hears about relevant txs; version.relay=False without a filter
        # suppresses tx invs entirely (net_processing.cpp SendMessages)
        tx = None
        fee_rate = 0
        if inv_type == MSG_TX:
            with self.node.cs_main:
                entry = self.node.mempool.get(h)
                if entry is not None:
                    tx = entry.tx
                    fee_rate = entry.fee * 1000 // max(entry.size, 1)
                    # mapRelay: remember for serving getdata post-mining
                    self._relay_memory[h] = (
                        tx, time.time() + RELAY_TX_CACHE_TIME)

        def _want(peer: Peer) -> bool:
            if inv_type != MSG_TX:
                return True
            if peer.min_fee_filter and fee_rate < peer.min_fee_filter:
                return False  # BIP133
            if peer.bloom_filter is not None:
                return tx is not None and \
                    peer.bloom_filter.is_relevant_and_update(tx)
            return peer.relay_txs

        def _do():
            for peer in self.peers.values():
                if peer.id == skip_peer or not peer.handshaked:
                    continue
                if h in peer.known_invs or not _want(peer):
                    continue
                peer.known_invs.add(h)
                try:
                    peer.send("inv", ser_inv([(inv_type, h)]))
                except Exception:
                    pass
        self.loop.call_soon_threadsafe(_do)

    def relay_block(self, h: bytes, skip_peer: int = 0) -> None:
        self._broadcast_inv(MSG_BLOCK, h, skip_peer)

    def relay_tx(self, h: bytes, skip_peer: int = 0) -> None:
        self._broadcast_inv(MSG_TX, h, skip_peer)
