"""Flag and configuration handling — the ArgsManager analogue.

Reference: src/util.cpp:~400-600 (ParseParameters, ReadConfigFile, GetArg /
GetBoolArg / GetArgs, SoftSetArg), src/chainparamsbase.cpp (network
selection / datadir subdirectories), src/init.cpp:~350-600 (HelpMessage).

Bitcoin-style flags: `-name=value` or bare `-name` (boolean true); a
leading `-no` negates (`-nolisten` == `-listen=0`). Precedence is
CLI > config file, matching the reference (config-file values are
soft-set only where the CLI didn't supply the arg). `--name` is accepted
as an alias for `-name` (the reference strips the extra dash too), which
is how `--tpu` arrives.
"""

from __future__ import annotations

import os
from dataclasses import replace
from typing import Optional

from ..consensus.params import ChainParams, select_params
from ..consensus.serialize import hex_to_hash

DEFAULT_DATADIR = "~/.bitcoincashplus-tpu"

HELP_MESSAGE = """\
bcpd — TPU-native bitcoincashplus node daemon

Options:
  -?, -help              Print this help message and exit
  -datadir=<dir>         Specify data directory (default: ~/.bitcoincashplus-tpu)
  -conf=<file>           Config file name (default: bitcoin.conf in datadir)
  -regtest               Use the regression test network
  -testnet               Use the test network
  -reindex               Rebuild chain state and block index from blk*.dat files
  -txindex               Maintain a full transaction index (default: 0)
  -par=<n>               Script verification batch backend threads; 0 = auto (default: 0)
  -dbcache=<n>           Database cache size in MiB (default: 300)
  -coinshards=<n>        Hash-partition fan-out of the sharded chainstate
                         store: power of two in [1, 256] (default: 4). An
                         existing sharded datadir's manifest pins the count;
                         legacy single-file datadirs stay on the old layout
                         until -reindex
  -coinswal              Per-shard WAL commit discipline: sync'd shard
                         flushes fsync the sqlite WAL at COMMIT
                         (synchronous=FULL) instead of running a full
                         wal_checkpoint per flush. Equal durability for
                         committed batches; trades checkpoint latency in
                         the parallel shard flush for WAL-fsync latency
                         at commit (default: 0)
  -assumeutxo=<hash:muhash>  Authorize loadtxoutset to adopt a UTXO snapshot
                         with exactly this tip block hash and MuHash set
                         digest (both 32-byte hex). The node serves at the
                         snapshot tip immediately while background
                         validation replays history into a shadow
                         chainstate and promotes on digest equality
  -snapshotepoch=<n>     Epoch stride (blocks) for the proof-carrying
                         snapshot certificate built at dumptxoutset: the
                         certified MuHash trajectory commits one digest
                         checkpoint every <n> blocks (default: 64)
  -snapshotspotcheck=<k> Background snapshot validation re-runs full script
                         checks on only <k> seeded-drawn certified epochs
                         (the final epoch always included) instead of all
                         of history; certificate digest tripwires still
                         fire at every epoch boundary (default: 0 = full
                         re-validation)
  -snapshotcertrequired  Refuse loadtxoutset snapshots that carry no
                         certificate instead of loading them quarantined
                         (default: 0)
  -checkblocks=<n>       How many blocks to verify at startup (default: 6)
  -checklevel=<n>        How thorough the startup block verification is (0-4, default: 3)
  -assumevalid=<hex>     Skip script verification for ancestors of this block
                         (0 = verify everything)
  -debug=<category>      Enable debug logging (all|net|mempool|rpc|bench|db|validation|tpu)
  -printtoconsole        Send trace/debug info to console instead of debug.log only
  -logjson               Write debug.log records as JSON objects stamped with the
                         active telemetry span's correlation id (default: 0)
  -telemetry=<level>     Telemetry level: off = disabled, counters = metrics
                         registry (getmetrics RPC + /metrics Prometheus text;
                         default, <2% overhead), trace = counters + pipeline
                         span tracing (dumptrace RPC / -tracefile); unknown
                         values are rejected at startup
  -tracefile=<path>      Dump the span trace (Chrome/perfetto JSON) to <path>
                         at shutdown; implies -telemetry=trace (an explicit
                         lower -telemetry level alongside it is rejected)
  -maxmempool=<n>        Max transaction memory pool size in MiB (default: 300)
  -mempoolexpiry=<n>     Do not keep transactions in mempool longer than <n> hours (default: 336)
  -mempoolbatch=<0|1>    Batch-shaped mempool: numpy aggregate columns,
                         incremental mining/eviction frontiers, staged bulk
                         removal (default: 1; 0 pins the per-tx reference
                         paths — the differential-test control)
  -mempoolselfcheck=<0|1>
                         Re-derive every batched template-selection and
                         eviction verdict through the per-tx oracle and log
                         divergence (debug, like -checkmempool; default: 0)
  -minrelaytxfee=<amt>   Minimum relay fee rate in satoshis/kB (default: 1000)
  -tpu=<0|1>             Use the TPU batch backend for sig verification and
                         mining sweeps (default: auto-detect)
  -ecdsakernel=<glv|w4|msm>
                         Device ECDSA verify kernel: glv = endomorphism-split
                         ladder + fixed-base G comb (default), w4 = the
                         64-window kernel (kept as oracle/fallback), msm =
                         Pippenger multi-scalar batch check for SCHNORR lanes
                         (one point-at-infinity verdict per batch; rejected
                         batches bisect to the per-lane oracle — worth it from
                         a few dozen Schnorr sigs per batch, ECDSA lanes keep
                         riding glv); unknown values are rejected at startup
  -compilecache=<dir>    Persistent XLA compilation cache directory (default:
                         off). First compile of each kernel shape writes the
                         cache; every later process start reads it instead of
                         re-paying the ~90 s cold GLV compile. Seeds
                         BCP_COMPILE_CACHE for child processes; cache hits
                         surface in gettpuinfo.device.compilation_cache
  -residentminer=<on|off|force>  Device-resident mining loop: the nonce sweep
                         runs as a persistent segment pipeline over
                         long-lived template buffers (refresh = buffer swap,
                         not a new dispatch). Default: on; off = the
                         per-dispatch sweep; force = resident even on a
                         regtest CPU node (test/bench hook — those otherwise
                         keep the scalar host fast path); unknown values are
                         rejected at startup
  -sigservice=<on|off>   Run the always-on micro-batching signature service:
                         mempool ingest and tip relay enqueue script checks
                         into shared device lanes behind a flush deadline
                         (default: on; off = synchronous verification,
                         verdicts identical)
  -sigservicedeadline=<ms>  Max milliseconds a partial signature bucket may
                         wait for more lanes before flushing (default: 4;
                         0 = flush on every enqueue)
  -sigservicelanes=<n>   Signature-service bucket size in lanes (default:
                         2046 — fills the 2048 device bucket with the two
                         known-answer probe lanes)
  -port=<port>           Listen for P2P connections on <port>
  -listen                Accept P2P connections from outside (default: 1 when P2P enabled)
  -connect=<ip:port>     Connect only to the specified node (may be repeated)
  -banscore=<n>          Ban-score threshold: misbehaving peers are evicted
                         once their score reaches <n> (default: 100)
  -blockdownloadtimeout=<n>  Seconds without download progress before a peer
                         with blocks in flight counts as stalling (default: 60)
  -maxrecvrate=<n>       Per-peer receive ceiling in bytes/sec averaged over
                         one supervision tick; 0 = unlimited (default: 4000000)
  -maxunconnectingheaders=<n>  Charge the non-connecting-headers misbehavior
                         only every <n>th offense since the peer's last
                         connecting batch (default: 10)
  -nettick=<n>           P2P supervision tick interval in seconds (default: 5)
  -netseed=<n>           Seed for the network rng (orphan eviction); -1 = OS
                         entropy (default: -1)
  -backfilltimeout=<n>   Seconds before an assumeutxo backfill request is
                         torn off its peer and retried on another (default:
                         min(10, -blockdownloadtimeout))
  -rpcport=<port>        Listen for JSON-RPC connections on <port>
  -rpcbind=<addr>        Bind RPC to address (default: 127.0.0.1)
  -rpcuser=<user>        Username for JSON-RPC connections (default: cookie auth)
  -rpcpassword=<pw>      Password for JSON-RPC connections
  -server                Accept JSON-RPC commands (default: 1 for bcpd)
  -gateway=<port>        Run the fleet serving front door on <port>: a
                         load-balancing JSON-RPC gateway over the -replicas
                         pool with per-client token-bucket admission,
                         graduated shedding, request coalescing and
                         mid-request failover (default: off)
  -replicas=<host:port,...>  Read-replica RPC endpoints behind -gateway
                         (snapshot-bootstrapped bcpd nodes sharing this
                         node's -rpcuser/-rpcpassword)
  -maxreplicalag=<n>     Consistency gate: rotate a replica out of serving
                         once its probed tip lags the pool fan-out height
                         by more than <n> blocks (default: 2)
  -gatewayrate=<n>       Per-client admission refill in requests/sec
                         (default: 500); -gatewayburst=<n> bucket capacity
                         (default: 200); -gatewaysoft/-gatewayhard in-flight
                         ceilings where read-only / all traffic sheds
                         (defaults: 64 / 256)
  -flushinterval=<n>     Flush chainstate every <n> connected blocks (default: 64)
"""


class ConfigError(Exception):
    pass


class Config:
    """Parsed arguments + config file, with typed accessors."""

    def __init__(self, argv: Optional[list[str]] = None):
        # name -> list of values; CLI wins over conf (soft-set semantics)
        self.args: dict[str, list[str]] = {}
        if argv:
            self.parse_args(argv)

    # -- parsing -------------------------------------------------------

    @staticmethod
    def _split(arg: str) -> tuple[str, str]:
        key, _, value = arg.partition("=")
        key = key.lstrip("-")
        if not _:
            value = "1"
        if key.startswith("no"):  # -nofoo => -foo=0  (InterpretNegatedOption)
            return key[2:], "0" if value == "1" else "1"
        return key, value

    def parse_args(self, argv: list[str]) -> None:
        """ParseParameters. Raises ConfigError on non-flag positionals."""
        for arg in argv:
            if not arg.startswith("-"):
                raise ConfigError(f"unexpected argument: {arg!r}")
            key, value = self._split(arg)
            self.args.setdefault(key, []).append(value)

    def read_config_file(self, path: Optional[str] = None) -> None:
        """ReadConfigFile — ini-style `name=value` lines, '#' comments.
        Values soft-set: the CLI keeps precedence."""
        if path is None:
            path = os.path.join(self.datadir_base, self.get("conf", "bitcoin.conf"))
        if not os.path.exists(path):
            return
        file_args: dict[str, list[str]] = {}
        with open(path) as f:
            for raw in f:
                line = raw.split("#", 1)[0].strip()
                if not line:
                    continue
                if "=" not in line:
                    raise ConfigError(f"parse error in {path}: {raw.strip()!r}")
                key, value = line.split("=", 1)
                file_args.setdefault(key.strip().lstrip("-"), []).append(value.strip())
        for key, values in file_args.items():
            if key not in self.args:
                self.args[key] = values

    # -- typed accessors (GetArg family) -------------------------------

    def get(self, name: str, default: str = "") -> str:
        values = self.args.get(name)
        return values[0] if values else default

    def get_multi(self, name: str) -> list[str]:
        return list(self.args.get(name, ()))

    def get_bool(self, name: str, default: bool = False) -> bool:
        values = self.args.get(name)
        if not values:
            return default
        return values[0] not in ("0", "false", "")

    def get_int(self, name: str, default: int = 0) -> int:
        values = self.args.get(name)
        if not values:
            return default
        try:
            return int(values[0])
        except ValueError:
            raise ConfigError(f"-{name}={values[0]!r}: not an integer") from None

    def has(self, name: str) -> bool:
        return name in self.args

    # -- derived settings ----------------------------------------------

    @property
    def network(self) -> str:
        if self.get_bool("regtest"):
            return "regtest"
        if self.get_bool("testnet"):
            return "test"
        return "main"

    @property
    def datadir_base(self) -> str:
        return os.path.expanduser(self.get("datadir", DEFAULT_DATADIR))

    @property
    def datadir(self) -> str:
        """Network subdirectory, as GetDataDir(fNetSpecific=true) lays out."""
        sub = {"main": "", "test": "testnet3", "regtest": "regtest"}[self.network]
        return os.path.join(self.datadir_base, sub) if sub else self.datadir_base

    def chain_params(self) -> ChainParams:
        """SelectParams + -assumevalid override (src/init.cpp AppInitMain)."""
        params = select_params(self.network)
        if self.has("assumevalid"):
            raw = self.get("assumevalid")
            av = None if raw in ("0", "") else hex_to_hash(raw)
            params = replace(params, assume_valid=av)
        if self.has("minimumchainwork"):
            params = replace(
                params, minimum_chain_work=int(self.get("minimumchainwork"), 16)
            )
        return params

    @property
    def tpu_backend(self) -> str:
        """Backend policy for ecdsa_batch / the mining sweep: the `--tpu`
        graft flag (SURVEY.md §6.6). Unset = 'auto' (use a device when one
        is present), -tpu=1 forces device, -tpu=0 forces CPU."""
        if not self.has("tpu"):
            return "auto"
        return "tpu" if self.get_bool("tpu") else "cpu"

    def rpc_port(self, params: ChainParams) -> int:
        return self.get_int("rpcport", params.rpc_port)

    def p2p_port(self, params: ChainParams) -> int:
        return self.get_int("port", params.default_port)
